"""CoreWorker: the in-process runtime embedded in every driver and worker.

Reference: ``src/ray/core_worker`` — task submission with lease-then-push
(``task_submission/normal_task_submitter.cc:32``, lease reuse per scheduling
key), actor task submission with per-caller ordered queues
(``actor_task_submitter.cc``), task execution (``task_receiver.cc``), the
in-memory store for small results, the plasma provider for large ones, task
retries + lineage (``task_manager.cc``), and the gRPC service
(``HandlePushTask`` core_worker.cc:3360).

Round-1 deviations (documented; see SURVEY.md §7 hard parts):
- distributed refcounting is deferred: objects are freed explicitly or when
  the owning job exits (the store's LRU spill bounds memory meanwhile);
- object locations resolve via the GCS directory plus a direct owner fetch
  for small objects, rather than the reference's ownership directory.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import pickle
import threading
import time
import traceback
import uuid
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private.common import ActorOptions, TaskOptions, TaskSpec
from ray_tpu._private.config import RAY_CONFIG
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_store import SegmentCache, pack_blob, plan_layout, read_blob, write_blob, ShmSegment
from ray_tpu._private.rpc import (
    RpcApplicationError,
    RpcError,
    RpcServer,
    RetryingRpcClient,
)
from ray_tpu._private.serialization import deserialize, serialize
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    TaskError,
)
from ray_tpu.object_ref import ObjectRef

logger = logging.getLogger("ray_tpu.worker")

_LEASE_IDLE_S = 2.0


def _freeze(d: Dict[str, float]) -> tuple:
    return tuple(sorted(d.items()))


class _ActorView:
    """Owner-side view of one actor (reference: actor_task_submitter.cc)."""

    def __init__(self, actor_id: ActorID):
        self.actor_id = actor_id
        self.state = "PENDING_CREATION"
        self.address = ""
        self.seqno = 0
        self.client: Optional[RetryingRpcClient] = None
        self.state_changed = asyncio.Event()
        self.max_task_retries = 0
        self.death_cause = ""


class _LeasePool:
    """Per-scheduling-key worker lease pool (reference: the SchedulingKey
    queues in normal_task_submitter.cc — pipelined lease requests capped at
    max_pending_lease_requests, granted workers reused for queued tasks of
    the same shape, returned to the raylet after an idle timeout)."""

    def __init__(self, core: "CoreWorker", key, opts, resources):
        self.core = core
        self.key = key
        self.opts = opts
        self.resources = resources
        self.idle: List[dict] = []
        self.waiters: "asyncio.Queue[asyncio.Future]" = None  # lazily via deque
        from collections import deque

        self._waiters = deque()
        self.in_flight = 0
        self._reaper: Optional[asyncio.Task] = None

    async def acquire(self) -> dict:
        if self.idle:
            return self.idle.pop()
        fut = self.core.loop.create_future()
        self._waiters.append(fut)
        self._maybe_request()
        result = await fut
        if isinstance(result, Exception):
            raise result
        return result

    def _maybe_request(self):
        cap = RAY_CONFIG.max_pending_lease_requests
        while self.in_flight < min(len(self._waiters), cap):
            self.in_flight += 1
            asyncio.ensure_future(self._request_one())

    async def _request_one(self):
        try:
            lease = await self._do_request()
        except Exception as e:
            self.in_flight -= 1
            while self._waiters:
                fut = self._waiters.popleft()
                if not fut.done():
                    fut.set_result(e)
                    break
            return
        self.in_flight -= 1
        self._hand_out(lease)

    def _hand_out(self, lease: dict):
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(lease)
                return
        lease["last_used"] = time.monotonic()
        self.idle.append(lease)
        if self._reaper is None or self._reaper.done():
            self._reaper = asyncio.ensure_future(self._reap_idle())

    def release(self, lease: dict):
        self._hand_out(lease)

    async def discard(self, lease: dict):
        await self.core._drop_lease(lease)
        self._maybe_request()

    async def _reap_idle(self):
        while self.idle or self._waiters or self.in_flight:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            keep = []
            for lease in self.idle:
                if now - lease["last_used"] > _LEASE_IDLE_S:
                    await self.core._drop_lease(lease)
                else:
                    keep.append(lease)
            self.idle = keep

    async def _do_request(self) -> dict:
        opts, resources = self.opts, self.resources
        node = await self.core._pick_node(opts, resources)
        if node is None:
            raise RuntimeError(f"no feasible node for resources={resources} "
                               f"selector={opts.label_selector}")
        raylet = self.core._raylet_client(node["address"])
        req = {
            "resources": resources,
            "label_selector": opts.label_selector,
            "job_id": self.core.job_id,
            "pg": opts.placement_group.id.binary() if opts.placement_group else None,
            "bundle_index": opts.placement_group_bundle_index,
            "runtime_env": opts.runtime_env,
        }
        deadline = time.monotonic() + RAY_CONFIG.worker_start_timeout_s * 4
        while True:
            reply = pickle.loads(await raylet.call(
                "RequestWorkerLease", pickle.dumps(req),
                timeout=RAY_CONFIG.worker_start_timeout_s + 30))
            if reply["status"] == "granted":
                return {"key": self.key, "lease_id": reply["lease_id"],
                        "worker_address": reply["worker_address"],
                        "raylet_address": node["address"],
                        "last_used": time.monotonic()}
            if time.monotonic() > deadline:
                raise RuntimeError(f"lease request kept failing: {reply['status']}")
            if reply["status"] in ("busy", "infeasible"):
                node2 = await self.core._pick_node(opts, resources)
                if node2 is not None and node2["address"] != node["address"]:
                    node = node2
                    raylet = self.core._raylet_client(node["address"])
                await asyncio.sleep(0.1)


class CoreWorker:
    """One instance per process; drives all cluster interaction."""

    mode = "cluster"

    def __init__(
        self,
        gcs_address: str,
        raylet_address: Optional[str],
        node_id: Optional[NodeID],
        is_driver: bool,
        namespace: str = "default",
        loop: Optional[asyncio.AbstractEventLoop] = None,
        session_dir: str = "",
    ):
        self.gcs_address = gcs_address
        self.raylet_address = raylet_address
        self.node_id = node_id
        self.is_driver = is_driver
        self.namespace = namespace
        self.worker_id = WorkerID.from_random()
        self.session_dir = session_dir
        self.job_id: JobID = JobID.nil()
        self._owned_loop = loop is None
        self.loop = loop or asyncio.new_event_loop()
        self._loop_thread: Optional[threading.Thread] = None
        self.server: Optional[RpcServer] = None
        self.address = ""
        self.gcs: Optional[RetryingRpcClient] = None
        self.raylet: Optional[RetryingRpcClient] = None
        self._raylet_clients: Dict[str, RetryingRpcClient] = {}
        self._worker_clients: Dict[str, RetryingRpcClient] = {}
        # owner state
        self.memory_store: Dict[ObjectID, Any] = {}
        self._result_futures: Dict[ObjectID, asyncio.Future] = {}
        self._in_store: Dict[ObjectID, bool] = {}
        self._tasks: Dict[TaskID, dict] = {}  # lineage / retry records
        self._lease_cache: Dict[tuple, List[dict]] = {}
        self._renv_prepared: Dict[str, dict] = {}
        self.job_runtime_env: Optional[dict] = None
        self._actors: Dict[ActorID, _ActorView] = {}
        self._actor_name_cache: Dict[ActorID, tuple] = {}
        self._pushed_functions: set = set()
        self._put_index = 0
        self._spread_hint = 0
        self.segments = SegmentCache()
        # executor state
        self._fn_cache: Dict[str, Any] = {}
        self.actor_instance = None
        self.actor_id: Optional[ActorID] = None
        # device-object transport (reference: per-actor GPUObjectStore):
        # values produced by tensor_transport-marked methods stay here
        self.device_store: Dict[bytes, Any] = {}
        self._device_fetch_cache: Dict[bytes, Any] = {}
        self._actor_async = False
        self._exec_pool = None
        self._exec_lock = threading.Lock()
        self._order_buf: Dict[str, dict] = {}
        self._tls = threading.local()
        self._shutdown = False
        self.node_hex = node_id.hex() if node_id else ""

    # ------------------------------------------------------------------
    # loop plumbing
    # ------------------------------------------------------------------

    def _start_loop(self):
        if self._loop_thread is not None or not self._owned_loop:
            return
        self._loop_thread = threading.Thread(
            target=self.loop.run_forever, name="ray_tpu-io", daemon=True
        )
        self._loop_thread.start()

    def _run(self, coro, timeout=None):
        """Run a coroutine on the io loop from any user thread."""
        if threading.current_thread() is self._loop_thread:
            raise RuntimeError("blocking call on the io loop")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    # ------------------------------------------------------------------
    # connect
    # ------------------------------------------------------------------

    def connect(self):
        self._start_loop()
        self._run(self._connect())
        return self

    async def _connect(self):
        self.server = RpcServer(self._handle_rpc)
        self.address = await self.server.start()
        self.gcs = RetryingRpcClient(
            self.gcs_address, on_push=self._on_push, on_reconnect=self._on_gcs_reconnect
        )
        if self.is_driver:
            reply = pickle.loads(await self.gcs.call("RegisterDriver", pickle.dumps({
                "address": self.address,
                "namespace": self.namespace,
                "entrypoint": " ".join(os.sys.argv[:2]),
            })))
            self.job_id = JobID(reply["job_id"])
        channels = ["actors"]
        if self.is_driver and getattr(self, "log_to_driver", False):
            channels.append("logs")
        await self.gcs.call("Subscribe", pickle.dumps({"channels": channels}))
        if self.raylet_address:
            self.raylet = RetryingRpcClient(self.raylet_address)
        else:
            # pick the head node's raylet as our local raylet
            nodes = pickle.loads(await self.gcs.call("GetAllNodes", b""))["nodes"]
            head = next((n for n in nodes if n["is_head"]), nodes[0] if nodes else None)
            if head is None:
                raise RuntimeError("no raylets registered with the GCS")
            self.raylet_address = head["address"]
            self.node_hex = head["node_id"]
            self.raylet = RetryingRpcClient(self.raylet_address)

    async def _on_gcs_reconnect(self, client):
        try:
            channels = ["actors"]
            if self.is_driver and getattr(self, "log_to_driver", False):
                channels.append("logs")
            await client.call("Subscribe", pickle.dumps({"channels": channels}))
        except Exception:
            logger.warning("GCS reconnect: re-subscribe failed", exc_info=True)
        if self.is_driver and not self.job_id.is_nil():
            # re-bind this connection to our job after a GCS restart so
            # driver-disconnect cleanup still fires (GCS FT)
            for _ in range(3):
                try:
                    await client.call("ReattachDriver", pickle.dumps(
                        {"job_id": self.job_id.binary()}))
                    break
                except Exception:
                    logger.warning("GCS reconnect: ReattachDriver failed",
                                   exc_info=True)
                    await asyncio.sleep(0.2)

    def _on_push(self, channel: str, payload: bytes):
        msg = pickle.loads(payload)
        if channel == "logs":
            import sys as _sys

            node = msg.get("node", "?")
            for line in msg.get("lines", []):
                print(f"\x1b[2m({node})\x1b[0m {line}", file=_sys.stderr)
            return
        if channel == "actors":
            info = msg.get("info", {})
            aid = ActorID.from_hex(info["actor_id"])
            view = self._actors.get(aid)
            if view is not None:
                if info["address"] != view.address:
                    view.seqno = 0  # new incarnation
                view.state = info["state"]
                view.address = info["address"]
                view.death_cause = info.get("death_cause", "")
                view.client = None
                ev, view.state_changed = view.state_changed, asyncio.Event()
                ev.set()

    # ------------------------------------------------------------------
    # clients
    # ------------------------------------------------------------------

    def _raylet_client(self, address: str) -> RetryingRpcClient:
        if address == self.raylet_address:
            return self.raylet
        c = self._raylet_clients.get(address)
        if c is None:
            c = RetryingRpcClient(address)
            self._raylet_clients[address] = c
        return c

    def _worker_client(self, address: str) -> RetryingRpcClient:
        c = self._worker_clients.get(address)
        if c is None:
            c = RetryingRpcClient(address)
            self._worker_clients[address] = c
        return c

    async def _gcs_call(self, method: str, req: dict, timeout=None) -> dict:
        return pickle.loads(await self.gcs.call(method, pickle.dumps(req), timeout=timeout))

    # ------------------------------------------------------------------
    # function / class table
    # ------------------------------------------------------------------

    async def _prepare_runtime_env(self, renv):
        """Normalize + upload runtime-env packages once (driver side;
        reference: runtime_env/working_dir.py upload + uri_cache.py)."""
        import json as _json

        from ray_tpu._private import runtime_env as renv_mod

        if renv is None:
            renv = getattr(self, "job_runtime_env", None)
        renv = renv_mod.normalize(renv)
        if not renv:
            return None
        cache_key = _json.dumps(renv, sort_keys=True)
        cached = self._renv_prepared.get(cache_key)
        if cached is not None:
            return cached
        out = dict(renv)

        async def upload(path):
            if isinstance(path, dict):  # already a KV reference
                return path
            sha, blob, base = renv_mod.package_dir(path)
            key = f"pkg:{sha}"
            reply = await self._gcs_call("KVGet", {"ns": "renv", "key": key})
            if reply["value"] is None:
                await self._gcs_call("KVPut", {"ns": "renv", "key": key,
                                               "value": blob})
            return {"kv_key": key, "sha": sha, "base": base}

        if "working_dir" in out:
            out["working_dir"] = await upload(out["working_dir"])
        if "py_modules" in out:
            out["py_modules"] = [await upload(p) for p in out["py_modules"]]
        self._renv_prepared[cache_key] = out
        return out

    async def _push_function(self, obj) -> str:
        blob = cloudpickle.dumps(obj)
        key = hashlib.sha1(blob).hexdigest()
        if key not in self._pushed_functions:
            await self._gcs_call("KVPut", {"ns": "fn", "key": key, "value": blob,
                                           "overwrite": False})
            self._pushed_functions.add(key)
        return key

    async def _fetch_function(self, key: str):
        fn = self._fn_cache.get(key)
        if fn is None:
            reply = await self._gcs_call("KVGet", {"ns": "fn", "key": key})
            if reply["value"] is None:
                raise RuntimeError(f"function {key} not found in GCS")
            fn = cloudpickle.loads(reply["value"])
            self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    # objects: put / get / wait
    # ------------------------------------------------------------------

    def _next_put_id(self) -> ObjectID:
        self._put_index += 1
        base = TaskID(self.worker_id.binary()[: TaskID.SIZE - 4] + self.job_id.binary())
        return ObjectID.from_put(base, self._put_index % 0x7FFF)

    def put(self, value: Any) -> ObjectRef:
        oid = self._next_put_id()
        self._run(self._put_value(oid, value))
        return ObjectRef(oid, self.address)

    async def _put_value(self, oid: ObjectID, value: Any):
        inband, buffers = serialize(value)
        total = len(inband) + sum(b.nbytes for b in buffers)
        if total < RAY_CONFIG.object_inline_max_bytes:
            self.memory_store[oid] = value
            return
        await self._store_blob(oid, inband, buffers)
        self._in_store[oid] = True

    async def _store_blob(self, oid: ObjectID, inband: bytes, buffers,
                          attempt: int = 0):
        total, offsets = plan_layout(inband, buffers)
        reply = pickle.loads(await self.raylet.call("StoreCreate", pickle.dumps(
            {"oid": oid.binary(), "size": total, "attempt": attempt})))
        if reply["status"] in ("exists", "stale_attempt"):
            # seal-once: the id is already (or about to be) bound to a value
            # for this or a newer execution epoch; this writer stands down
            return
        if reply["status"] != "ok":
            raise ObjectLostError(f"object store rejected {oid.hex()}: {reply}")
        if "arena_name" in reply:
            # native arena backend: write into the shared arena at the offset
            seg = self.segments.open(reply["arena_name"])
            off = reply["offset"]
            region = memoryview(seg.buf)[off : off + total]
            write_blob(region, inband, buffers, offsets)
        else:
            seg = ShmSegment(reply["shm_name"])
            try:
                write_blob(seg.buf, inband, buffers, offsets)
            finally:
                seg.close()
        await self.raylet.call("StoreSeal", pickle.dumps(
            {"oid": oid.binary(), "attempt": attempt}))

    async def _read_local_store(self, oid: ObjectID, timeout: float, pull=True):
        reply = pickle.loads(await self.raylet.call("StoreGet", pickle.dumps(
            {"oid": oid.binary(), "timeout": timeout, "pull": pull}),
            timeout=timeout + 10.0))
        status = reply["status"]
        if status == "inline":
            inband, buffers = read_blob(reply["blob"])
            return True, deserialize(inband, buffers)
        if status == "shm":
            seg = self.segments.open(reply["shm_name"])
            inband, buffers = read_blob(seg.buf)
            return True, deserialize(inband, buffers)
        if status == "shm_arena":
            seg = self.segments.open(reply["arena_name"])
            off, size = reply["offset"], reply["size"]
            region = memoryview(seg.buf)[off : off + size]
            inband, buffers = read_blob(region)
            return True, deserialize(inband, buffers)
        return False, None

    async def _get_one(self, ref: ObjectRef, deadline: float) -> Any:
        oid = ref.id
        while True:
            # 1. local memory store (own small results)
            if oid in self.memory_store:
                return self.memory_store[oid]
            # 2. a pending local task will produce it
            fut = self._result_futures.get(oid)
            if fut is not None and not fut.done():
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    raise GetTimeoutError(f"timed out waiting for {oid.hex()}")
                try:
                    await asyncio.wait_for(asyncio.shield(fut), timeout)
                except asyncio.TimeoutError:
                    raise GetTimeoutError(f"timed out waiting for {oid.hex()}")
                continue
            # 3. known to live in the distributed store
            if self._in_store.get(oid):
                ok, value = await self._read_local_store(
                    oid, max(0.1, deadline - time.monotonic()))
                if ok:
                    return value
                raise ObjectLostError(f"object {oid.hex()} lost from store")
            # 4. remote owner fetch (small objects / long-poll for pending)
            owner = ref.owner_address()
            if owner and owner != self.address:
                value, in_store = await self._fetch_from_owner(ref, deadline)
                if in_store:
                    ok, value = await self._read_local_store(
                        oid, max(0.1, deadline - time.monotonic()))
                    if ok:
                        return value
                    raise ObjectLostError(f"object {oid.hex()} lost from store")
                return value
            # 5. last resort: the store via directory pull
            ok, value = await self._read_local_store(
                oid, max(0.1, min(deadline - time.monotonic(), 5.0)))
            if ok:
                return value
            if time.monotonic() > deadline:
                raise GetTimeoutError(f"timed out resolving {oid.hex()}")

    async def _fetch_from_owner(self, ref: ObjectRef, deadline: float):
        client = self._worker_client(ref.owner_address())
        while True:
            timeout = deadline - time.monotonic()
            if timeout <= 0:
                raise GetTimeoutError(f"timed out fetching {ref.hex()} from owner")
            try:
                reply = pickle.loads(await client.call("GetOwnedObject", pickle.dumps(
                    {"oid": ref.binary(), "timeout": min(timeout, 10.0)}),
                    timeout=min(timeout, 10.0) + 5.0, retries=1))
            except (RpcError, asyncio.TimeoutError) as e:
                raise ObjectLostError(
                    f"owner {ref.owner_address()} of {ref.hex()} unreachable: {e}")
            status = reply["status"]
            if status == "value":
                inband, buffers = read_blob(reply["blob"])
                value = deserialize(inband, buffers)
                if isinstance(value, TaskError):
                    raise value
                return value, False
            if status == "in_store":
                return None, True
            if status == "error":
                raise pickle.loads(reply["error"])
            # pending: loop

    async def _maybe_pull_device(self, value, deadline):
        """Resolve a DeviceObjectMarker by pulling from the holder worker
        (zero-copy local hit when this worker IS the holder). Reference:
        gpu_object_manager orchestrating p2p pulls between actors."""
        from ray_tpu.experimental.device_objects import DeviceObjectMarker

        if not isinstance(value, DeviceObjectMarker):
            return value
        if value.address == self.address:
            if value.oid in self.device_store:
                return self.device_store[value.oid]
            raise ObjectLostError(
                f"device object {value.oid.hex()[:12]} was freed")
        cached = self._device_fetch_cache.get(value.oid)
        if cached is not None:
            return cached
        timeout = max(1.0, min(deadline - time.monotonic(), 300.0))
        try:
            reply = pickle.loads(await self._worker_client(value.address).call(
                "GetDeviceObject", pickle.dumps({"oid": value.oid}),
                timeout=timeout, retries=1, connect_timeout=5.0))
        except (RpcError, asyncio.TimeoutError) as e:
            raise ObjectLostError(
                f"holder {value.address} of device object "
                f"{value.oid.hex()[:12]} unreachable: {e}")
        if reply["status"] != "ok":
            self._device_fetch_cache.pop(value.oid, None)
            raise ObjectLostError(
                f"device object {value.oid.hex()[:12]} gone from holder "
                f"{value.address} (freed or actor restarted)")
        inband, buffers = read_blob(reply["blob"])
        fetched = deserialize(inband, buffers)
        if len(self._device_fetch_cache) > 32:
            self._device_fetch_cache.pop(next(iter(self._device_fetch_cache)))
        self._device_fetch_cache[value.oid] = fetched
        return fetched

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        deadline = time.monotonic() + (timeout if timeout is not None else 86400.0)

        async def _get_all():
            out = []
            for ref in refs:
                value = await self._get_one(ref, deadline)
                if isinstance(value, TaskError):
                    raise value
                out.append(await self._maybe_pull_device(value, deadline))
            return out

        values = self._run(_get_all())
        return values[0] if single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        async def _ready(ref) -> bool:
            oid = ref.id
            if oid in self.memory_store or self._in_store.get(oid):
                return True
            fut = self._result_futures.get(oid)
            if fut is not None:
                return fut.done()
            reply = pickle.loads(await self.raylet.call("StoreContains", pickle.dumps(
                {"oid": oid.binary()})))
            return reply["contains"]

        async def _wait():
            deadline = time.monotonic() + (timeout if timeout is not None else 86400.0)
            while True:
                flags = await asyncio.gather(*[_ready(r) for r in refs])
                ready = [r for r, f in zip(refs, flags) if f]
                if len(ready) >= num_returns or time.monotonic() >= deadline:
                    ready = ready[:num_returns]
                    rest = [r for r in refs if r not in ready]
                    return ready, rest
                await asyncio.sleep(0.01)

        return self._run(_wait())

    def as_future(self, ref):
        import concurrent.futures

        out: "concurrent.futures.Future" = concurrent.futures.Future()

        def _done(task):
            try:
                value = task.result()
                if isinstance(value, TaskError):
                    out.set_exception(value)
                else:
                    out.set_result(value)
            except Exception as e:
                out.set_exception(e)

        def _schedule():
            t = asyncio.ensure_future(self.await_ref(ref))
            t.add_done_callback(_done)

        self.loop.call_soon_threadsafe(_schedule)
        return out

    async def await_ref(self, ref):
        deadline = time.monotonic() + 86400.0
        value = await self._get_one(ref, deadline)
        if isinstance(value, TaskError):
            raise value
        return await self._maybe_pull_device(value, deadline)

    def free_objects(self, refs: List[ObjectRef]):
        from ray_tpu.experimental.device_objects import DeviceObjectMarker

        async def _free():
            oids = []
            for r in refs:
                # a marker in the memory store points at a device-held value:
                # release that too, or it would be orphaned forever
                value = self.memory_store.get(r.id)
                if isinstance(value, DeviceObjectMarker):
                    self._device_fetch_cache.pop(value.oid, None)
                    if value.address == self.address:
                        self.device_store.pop(value.oid, None)
                    else:
                        try:
                            await self._worker_client(value.address).call(
                                "FreeDeviceObject",
                                pickle.dumps({"oid": value.oid}),
                                timeout=10.0, retries=1)
                        except (RpcError, asyncio.TimeoutError, OSError):
                            pass
                self.memory_store.pop(r.id, None)
                self._in_store.pop(r.id, None)
                oids.append(r.binary())
            await self.raylet.call("StoreDelete", pickle.dumps({"oids": oids}))

        self._run(_free())

    # ------------------------------------------------------------------
    # task submission (owner side)
    # ------------------------------------------------------------------

    def submit_task(self, remote_fn, args, kwargs, opts: TaskOptions):
        task_id = TaskID.of(self.job_id)
        refs = [ObjectRef(ObjectID.for_task_return(task_id, i), self.address)
                for i in range(opts.num_returns)]
        self._run(self._submit_task_async(remote_fn, args, kwargs, opts, task_id, refs))
        return refs[0] if opts.num_returns == 1 else refs

    async def _submit_task_async(self, remote_fn, args, kwargs, opts, task_id, refs):
        opts.runtime_env = await self._prepare_runtime_env(opts.runtime_env)
        function_key = await self._push_function(remote_fn.function)
        args_blob = self._pack_args(args, kwargs)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            function_key=function_key,
            args_blob=args_blob,
            num_returns=opts.num_returns,
            options=opts,
            owner_address=self.address,
        )
        max_retries = opts.max_retries if opts.max_retries >= 0 else RAY_CONFIG.task_max_retries
        record = {"spec": spec, "attempts": 0, "max_retries": max_retries,
                  "refs": refs, "name": remote_fn.function_name}
        self._tasks[task_id] = record
        for ref in refs:
            self._result_futures[ref.id] = self.loop.create_future()
        asyncio.ensure_future(self._drive_task(record))

    def _pack_args(self, args, kwargs) -> bytes:
        # inline small owned values so the executor need not call back
        def _inline(v):
            if isinstance(v, ObjectRef) and v.id in self.memory_store:
                value = self.memory_store[v.id]
                if not isinstance(value, TaskError):
                    return value
            return v

        args = tuple(_inline(a) for a in args)
        kwargs = {k: _inline(v) for k, v in kwargs.items()}
        return pack_blob(*serialize((args, kwargs)))

    async def _drive_task(self, record: dict):
        """Submit with lease reuse; retry on worker failure (reference:
        normal_task_submitter.cc + task_manager.cc)."""
        spec: TaskSpec = record["spec"]
        opts: TaskOptions = spec.options
        resources = opts.required_resources()
        while True:
            try:
                pool, lease = await self._acquire_lease(opts, resources)
            except Exception as e:
                self._complete_error(record, TaskError(
                    f"scheduling failed for {record['name']}: {e}", traceback.format_exc()))
                return
            spec.attempt = record["attempts"]
            try:
                reply = pickle.loads(await self._worker_client(lease["worker_address"]).call(
                    "PushTask", pickle.dumps({"spec": spec}), timeout=86400.0, retries=0))
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                await pool.discard(lease)
                record["attempts"] += 1
                if record["attempts"] > record["max_retries"]:
                    self._complete_error(record, TaskError(
                        f"worker died running {record['name']} "
                        f"(after {record['attempts']} attempts): {e}", ""))
                    return
                logger.warning("retrying task %s (attempt %d): %s",
                               record["name"], record["attempts"], e)
                continue
            pool.release(lease)
            if reply["status"] == "ok":
                self._complete_ok(record, reply["results"])
                return
            err: TaskError = pickle.loads(reply["error"])
            if opts.retry_exceptions and record["attempts"] < record["max_retries"]:
                record["attempts"] += 1
                continue
            self._complete_error(record, err)
            return

    def _complete_ok(self, record, results):
        for ref, (kind, payload) in zip(record["refs"], results):
            if kind == "inline":
                inband, buffers = read_blob(payload)
                self.memory_store[ref.id] = deserialize(inband, buffers)
            else:  # stored in the distributed object store
                self._in_store[ref.id] = True
            fut = self._result_futures.get(ref.id)
            if fut is not None and not fut.done():
                fut.set_result(True)

    def _complete_error(self, record, err: TaskError):
        for ref in record["refs"]:
            self.memory_store[ref.id] = err
            fut = self._result_futures.get(ref.id)
            if fut is not None and not fut.done():
                fut.set_result(True)

    # -- leases --

    async def _acquire_lease(self, opts: TaskOptions, resources):
        from ray_tpu._private.runtime_env import env_hash

        key = (_freeze(resources), _freeze(opts.label_selector),
               opts.placement_group.id.binary() if opts.placement_group else None,
               opts.placement_group_bundle_index,
               env_hash(opts.runtime_env))
        pool = self._lease_cache.get(key)
        if pool is None:
            pool = _LeasePool(self, key, opts, resources)
            self._lease_cache[key] = pool
        lease = await pool.acquire()
        return pool, lease

    async def _pick_node(self, opts: TaskOptions, resources) -> Optional[dict]:
        strat = opts.scheduling_strategy
        if opts.placement_group is not None:
            reply = await self._gcs_call("GetPlacementGroup",
                                         {"pg_id": opts.placement_group.id.binary()})
            info = reply["info"]
            if info is None or info["state"] != "CREATED":
                # wait for the pg
                await self._gcs_call("WaitPlacementGroupReady", {
                    "pg_id": opts.placement_group.id.binary(), "timeout": 300.0},
                    timeout=310.0)
                reply = await self._gcs_call("GetPlacementGroup",
                                             {"pg_id": opts.placement_group.id.binary()})
                info = reply["info"]
                if info is None:
                    return None
            idx = max(opts.placement_group_bundle_index, 0)
            node_hex = info["bundle_nodes"][idx]
            nodes = (await self._gcs_call("GetAllNodes", {}))["nodes"]
            for n in nodes:
                if n["node_id"] == node_hex:
                    return {"node_id": node_hex, "address": n["address"]}
            return None
        selector = dict(opts.label_selector)
        req: Dict[str, Any] = {"resources": resources, "selector": selector}
        if strat is not None:
            if hasattr(strat, "node_id"):
                nodes = (await self._gcs_call("GetAllNodes", {}))["nodes"]
                for n in nodes:
                    if n["node_id"] == strat.node_id:
                        return {"node_id": strat.node_id, "address": n["address"]}
                return None
            if hasattr(strat, "hard"):
                selector.update(strat.hard)
                req["selector"] = selector
            if type(strat).__name__ == "SpreadSchedulingStrategy" or strat == "SPREAD":
                self._spread_hint += 1
                req["strategy"] = "SPREAD"
                req["spread_hint"] = self._spread_hint
        deadline = time.monotonic() + 300.0
        warned = False
        # one demand unit per concurrent pick, stable across its retries, so
        # the GCS autoscaler view counts waiters rather than poll attempts
        req.setdefault("waiter_id", uuid.uuid4().hex)
        while True:
            reply = await self._gcs_call("PickNode", req)
            if reply["node"] is not None:
                return reply["node"]
            if not warned:
                logger.warning("no feasible node yet for resources=%s selector=%s; waiting",
                               resources, selector)
                warned = True
            if time.monotonic() > deadline:
                return None
            await asyncio.sleep(0.5)

    async def _drop_lease(self, lease: dict):
        try:
            await self._raylet_client(lease["raylet_address"]).call(
                "ReturnWorkerLease", pickle.dumps({"lease_id": lease["lease_id"]}),
                timeout=5.0, retries=1)
        except (RpcError, asyncio.TimeoutError, OSError):
            pass

    # ------------------------------------------------------------------
    # actors (owner side)
    # ------------------------------------------------------------------

    def create_actor(self, actor_cls, args, kwargs, opts: ActorOptions):
        from ray_tpu.actor import ActorHandle

        actor_id = ActorID.of(self.job_id)
        info = self._run(self._create_actor_async(actor_cls, args, kwargs, opts, actor_id))
        aid = ActorID.from_hex(info["actor_id"])
        view = self._actors.setdefault(aid, _ActorView(aid))
        view.state = info["state"]
        view.address = info["address"]
        view.max_task_retries = opts.max_task_retries
        return ActorHandle(aid, actor_cls.method_names(), actor_cls.class_name,
                           opts.max_task_retries)

    async def _create_actor_async(self, actor_cls, args, kwargs, opts, actor_id):
        opts.runtime_env = await self._prepare_runtime_env(opts.runtime_env)
        function_key = await self._push_function(actor_cls.cls)
        task_id = TaskID.of(self.job_id)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            function_key=function_key,
            args_blob=self._pack_args(args, kwargs),
            num_returns=0,
            options=opts,
            owner_address=self.address,
            actor_id=actor_id,
            is_actor_creation=True,
            actor_options=opts,
        )
        reply = await self._gcs_call("CreateActor", {
            "spec": spec, "class_name": actor_cls.class_name})
        if reply["status"] == "name_taken":
            raise ValueError(f"actor name {opts.name!r} already taken")
        return reply["info"]

    def _actor_view(self, actor_id: ActorID) -> _ActorView:
        view = self._actors.get(actor_id)
        if view is None:
            view = _ActorView(actor_id)
            self._actors[actor_id] = view
            # seed state from GCS
            async def _seed():
                reply = await self._gcs_call("GetActorInfo", {"actor_id": actor_id.binary()})
                info = reply["info"]
                if info is not None and view.state == "PENDING_CREATION":
                    view.state = info["state"]
                    view.address = info["address"]
            asyncio.run_coroutine_threadsafe(_seed(), self.loop)
        return view

    def submit_actor_task(self, handle, method_name, args, kwargs, num_returns=1,
                          tensor_transport=""):
        task_id = TaskID.of(self.job_id)
        refs = [ObjectRef(ObjectID.for_task_return(task_id, i), self.address)
                for i in range(num_returns)]
        self._run(self._submit_actor_task_async(
            handle, method_name, args, kwargs, num_returns, task_id, refs,
            tensor_transport))
        return refs[0] if num_returns == 1 else refs

    async def _submit_actor_task_async(self, handle, method_name, args, kwargs,
                                       num_returns, task_id, refs,
                                       tensor_transport=""):
        view = self._actor_view(handle.actor_id)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            function_key="",
            args_blob=self._pack_args(args, kwargs),
            num_returns=num_returns,
            options=TaskOptions(num_returns=num_returns),
            owner_address=self.address,
            actor_id=handle.actor_id,
            method_name=method_name,
            tensor_transport=tensor_transport,
        )
        record = {"spec": spec, "attempts": 0,
                  "max_retries": handle._max_task_retries,
                  "refs": refs, "name": f"{handle._class_name}.{method_name}"}
        for ref in refs:
            self._result_futures[ref.id] = self.loop.create_future()
        asyncio.ensure_future(self._drive_actor_task(view, record))

    async def _drive_actor_task(self, view: _ActorView, record: dict):
        spec: TaskSpec = record["spec"]
        deadline = time.monotonic() + 3600.0
        while True:
            if view.state == "DEAD":
                self._complete_error(record, TaskError(
                    f"ActorDiedError: actor {view.actor_id.hex()[:12]} is dead "
                    f"({view.death_cause})", "", ActorDiedError(view.death_cause)))
                return
            if view.state != "ALIVE" or not view.address:
                # wait for restart / creation (reference: actor_task_submitter
                # queues calls while the actor is restarting)
                reply = await self._gcs_call("WaitActorReady", {
                    "actor_id": view.actor_id.binary(), "timeout": 60.0}, timeout=70.0)
                info = reply["info"]
                if info is None:
                    self._complete_error(record, TaskError(
                        "ActorDiedError: actor record missing", ""))
                    return
                if info["address"] != view.address:
                    # new incarnation: per-caller ordering restarts at 1
                    view.seqno = 0
                view.state, view.address = info["state"], info["address"]
                if time.monotonic() > deadline:
                    self._complete_error(record, TaskError(
                        "ActorUnavailableError: timed out waiting for actor", ""))
                    return
                continue
            try:
                # seqno is assigned at push time so ordering is per-incarnation
                # (a restarted actor's queue starts over at 1)
                view.seqno += 1
                spec.seqno = view.seqno
                spec.attempt = record["attempts"]
                # short connect timeout + one blind reconnect: the address came
                # from an ALIVE view, so an unreachable peer means the view is
                # stale — fail fast into the GCS recheck below (the real retry
                # loop) rather than camping on connect; the single presend
                # round covers the connect-then-instant-RST race on live peers
                reply = pickle.loads(await self._worker_client(view.address).call(
                    "PushTask", pickle.dumps({"spec": spec}), timeout=86400.0,
                    retries=0, connect_timeout=2.0, presend_retries=1))
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                view.state = "UNKNOWN"
                await asyncio.sleep(0.2)
                record["attempts"] += 1
                if record["attempts"] > max(record["max_retries"], 0):
                    self._complete_error(record, TaskError(
                        f"ActorUnavailableError: {record['name']} failed: {e}", "",
                        ActorUnavailableError(str(e))))
                    return
                continue
            if reply["status"] == "ok":
                self._complete_ok(record, reply["results"])
            else:
                self._complete_error(record, pickle.loads(reply["error"]))
            return

    def get_actor(self, name: str, namespace: Optional[str] = None):
        from ray_tpu.actor import ActorHandle

        reply = self._run(self._gcs_call("GetNamedActor", {
            "name": name, "namespace": namespace or self.namespace}))
        info = reply["info"]
        if info is None:
            raise ValueError(f"no actor named {name!r}")
        aid = ActorID.from_hex(info["actor_id"])
        view = self._actor_view(aid)
        view.state, view.address = info["state"], info["address"]
        return ActorHandle(aid, (), info.get("class_name", ""))

    def get_actor_handle(self, actor_id: ActorID):
        from ray_tpu.actor import ActorHandle

        return ActorHandle(actor_id, (), "")

    def kill_actor(self, handle, no_restart=True):
        self._run(self._gcs_call("KillActor", {
            "actor_id": handle.actor_id.binary(), "no_restart": no_restart}))

    def cancel(self, ref, force=False, recursive=True):
        pass  # cooperative cancellation lands with the C++ runtime tier

    # ------------------------------------------------------------------
    # cluster info
    # ------------------------------------------------------------------

    def cluster_resources(self):
        return self._run(self._gcs_call("GetClusterResources", {}))["total"]

    def available_resources(self):
        return self._run(self._gcs_call("GetClusterResources", {}))["available"]

    def nodes(self):
        return self._run(self._gcs_call("GetAllNodes", {}))["nodes"]

    def get_state(self):
        return self._run(self._gcs_call("GetState", {}))

    # ------------------------------------------------------------------
    # executor side (reference: task_execution/task_receiver.cc)
    # ------------------------------------------------------------------

    async def _handle_rpc(self, method: str, payload: bytes, conn) -> bytes:
        if method == "PushTask":
            req = pickle.loads(payload)
            return await self._handle_push_task(req["spec"])
        if method == "GetOwnedObject":
            return await self._handle_get_owned(pickle.loads(payload))
        if method == "Ping":
            return pickle.dumps({"status": "ok", "pid": os.getpid()})
        if method == "GetDeviceObject":
            req = pickle.loads(payload)
            value = self.device_store.get(req["oid"])
            if value is None and req["oid"] not in self.device_store:
                return pickle.dumps({"status": "gone"})
            # large device->host copies must not stall the event loop
            self._ensure_pool(1)
            inband, buffers = await self.loop.run_in_executor(
                self._exec_pool, serialize, value)
            return pickle.dumps({"status": "ok",
                                 "blob": pack_blob(inband, buffers)})
        if method == "FreeDeviceObject":
            req = pickle.loads(payload)
            freed = self.device_store.pop(req["oid"], None) is not None
            return pickle.dumps({"freed": freed})
        if method == "CheckActor":
            # GCS restart recovery probe: is the given actor instantiated
            # here? (dedups in-flight creations after an init-data replay)
            req = pickle.loads(payload)
            hosting = (self.actor_instance is not None
                       and self.actor_id is not None
                       and self.actor_id.binary() == req["actor_id"])
            return pickle.dumps({"hosting": hosting})
        if method == "Exit":
            self.loop.call_later(0.1, os._exit, 0)
            return pickle.dumps({"status": "ok"})
        raise RpcError(f"core worker: unknown method {method}")

    async def _handle_get_owned(self, req) -> bytes:
        oid = ObjectID(req["oid"])
        deadline = time.monotonic() + req.get("timeout", 10.0)
        while True:
            if oid in self.memory_store:
                value = self.memory_store[oid]
                if isinstance(value, TaskError):
                    return pickle.dumps({"status": "error", "error": pickle.dumps(value)})
                return pickle.dumps({"status": "value",
                                     "blob": pack_blob(*serialize(value))})
            if self._in_store.get(oid):
                return pickle.dumps({"status": "in_store"})
            fut = self._result_futures.get(oid)
            if fut is not None and not fut.done() and time.monotonic() < deadline:
                try:
                    await asyncio.wait_for(asyncio.shield(fut),
                                           deadline - time.monotonic())
                except asyncio.TimeoutError:
                    pass
                continue
            return pickle.dumps({"status": "pending"})

    async def _handle_push_task(self, spec: TaskSpec) -> bytes:
        if spec.is_actor_creation:
            return await self._exec_actor_creation(spec)
        if spec.actor_id is not None:
            return await self._exec_actor_task(spec)
        return await self._exec_normal_task(spec)

    def _ensure_pool(self, size: int, replace: bool = False):
        from concurrent.futures import ThreadPoolExecutor

        if self._exec_pool is None or (
                replace and self._exec_pool._max_workers < size):
            # a reused worker may carry a smaller pool from its task-executing
            # past; an actor with max_concurrency needs the full width
            self._exec_pool = ThreadPoolExecutor(max_workers=size,
                                                 thread_name_prefix="ray_tpu-exec")

    async def _exec_normal_task(self, spec: TaskSpec) -> bytes:
        if self.job_id.is_nil():
            self.job_id = spec.job_id
        fn = await self._fetch_function(spec.function_key)
        args, kwargs = await self._resolve_args(spec.args_blob)
        self._ensure_pool(1)
        t0 = time.time()
        result, err = await self.loop.run_in_executor(
            self._exec_pool, self._call_user_fn, fn, args, kwargs, spec)
        self._trace_task(spec, getattr(fn, "__name__", "task"), t0, err)
        return await self._pack_results(spec, result, err)

    def _trace_task(self, spec: TaskSpec, name: str, t0: float, err):
        """Span per executed task (reference: profile_event.cc into the
        task event buffer); no-op unless tracing is enabled."""
        from ray_tpu.util import tracing

        if not tracing.enabled():
            return
        if spec.actor_id is not None and spec.method_name:
            name = f"{type(self.actor_instance).__name__}.{spec.method_name}"                 if self.actor_instance is not None else spec.method_name
        tracing.record_span(
            name, t0, time.time(),
            category="actor_task" if spec.actor_id is not None else "task",
            task_id=spec.task_id.hex(), ok=err is None)

    def _call_user_fn(self, fn, args, kwargs, spec: TaskSpec):
        self._tls.task_id = spec.task_id
        try:
            result = fn(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = asyncio.run(result)
            return result, None
        except Exception as e:
            return None, TaskError(repr(e), traceback.format_exc())
        finally:
            self._tls.task_id = None

    async def _resolve_args(self, args_blob: bytes):
        inband, buffers = read_blob(args_blob)
        args, kwargs = deserialize(inband, buffers)

        async def _resolve(v):
            if isinstance(v, ObjectRef):
                value = await self._get_one(v, time.monotonic() + RAY_CONFIG.object_pull_timeout_s)
                if isinstance(value, TaskError):
                    raise value
                return await self._maybe_pull_device(
                    value, time.monotonic() + RAY_CONFIG.object_pull_timeout_s)
            return v

        args = [await _resolve(a) for a in args]
        kwargs = {k: await _resolve(v) for k, v in kwargs.items()}
        return args, kwargs

    async def _pack_results(self, spec: TaskSpec, result, err,
                            transport: str = "") -> bytes:
        if err is not None:
            return pickle.dumps({"status": "app_error", "error": pickle.dumps(err)})
        values: List[Any]
        if spec.num_returns == 0:
            values = []
        elif spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                err = TaskError(
                    f"task declared num_returns={spec.num_returns} but returned "
                    f"{len(values)} values", "")
                return pickle.dumps({"status": "app_error", "error": pickle.dumps(err)})
        results = []
        for i, value in enumerate(values):
            oid = ObjectID.for_task_return(spec.task_id, i)
            if transport:
                # the value stays resident here; ship a small marker instead
                from ray_tpu.experimental.device_objects import DeviceObjectMarker

                self.device_store[oid.binary()] = value
                value = DeviceObjectMarker(oid.binary(), self.address, transport)
            inband, buffers = serialize(value)
            total = len(inband) + sum(b.nbytes for b in buffers)
            if total < RAY_CONFIG.object_inline_max_bytes:
                results.append(("inline", pack_blob(inband, buffers)))
            else:
                await self._store_blob(oid, inband, buffers, spec.attempt)
                results.append(("store", None))
        return pickle.dumps({"status": "ok", "results": results})

    async def _exec_actor_creation(self, spec: TaskSpec) -> bytes:
        if self.job_id.is_nil():
            self.job_id = spec.job_id
        cls = await self._fetch_function(spec.function_key)
        args, kwargs = await self._resolve_args(spec.args_blob)
        opts = spec.actor_options
        self._ensure_pool(max(1, opts.max_concurrency), replace=True)
        self.actor_id = spec.actor_id

        def _create():
            try:
                self.actor_instance = cls(*args, **kwargs)
                return None
            except Exception as e:
                return TaskError(repr(e), traceback.format_exc())

        err = await self.loop.run_in_executor(self._exec_pool, _create)
        if err is not None:
            return pickle.dumps({"status": "app_error", "error": pickle.dumps(err)})
        self._actor_async = any(
            asyncio.iscoroutinefunction(getattr(self.actor_instance, n, None))
            for n in dir(self.actor_instance) if not n.startswith("__"))
        self._actor_sem = asyncio.Semaphore(max(1, opts.max_concurrency))
        return pickle.dumps({"status": "ok", "results": []})

    async def _wait_for_turn(self, spec: TaskSpec):
        """Per-caller seqno ordering (reference: actor_scheduling_queue.cc):
        start tasks in submission order; a missing seqno (failed send) only
        stalls successors for a bounded grace period."""
        state = self._order_buf.setdefault(spec.owner_address, {"expected": 1, "events": {}})
        if spec.seqno > state["expected"]:
            ev = state["events"].setdefault(spec.seqno, asyncio.Event())
            try:
                # bounded grace: a gap (lost predecessor) must not wedge the queue
                await asyncio.wait_for(ev.wait(), timeout=10.0)
            except asyncio.TimeoutError:
                pass
        state["expected"] = max(state["expected"], spec.seqno + 1)
        nxt = state["events"].pop(state["expected"], None)
        if nxt is not None:
            nxt.set()

    async def _exec_actor_task(self, spec: TaskSpec) -> bytes:
        if self.actor_instance is None:
            err = TaskError("ActorUnavailableError: actor instance not initialized", "")
            return pickle.dumps({"status": "app_error", "error": pickle.dumps(err)})
        if spec.seqno > 0:
            await self._wait_for_turn(spec)
        method = getattr(self.actor_instance, spec.method_name, None)
        if method is None:
            err = TaskError(f"AttributeError: no method {spec.method_name}", "")
            return pickle.dumps({"status": "app_error", "error": pickle.dumps(err)})
        # per-call options win over the decorator; "object" forces the
        # plain object-plane return (reference: ray.method override order)
        transport = (getattr(spec, "tensor_transport", "")
                     or getattr(method, "__ray_tpu_tensor_transport__", ""))
        if transport == "object":
            transport = ""
        args, kwargs = await self._resolve_args(spec.args_blob)
        t0 = time.time()
        if asyncio.iscoroutinefunction(method):
            async with self._actor_sem:
                try:
                    result, err = await method(*args, **kwargs), None
                except Exception as e:
                    result, err = None, TaskError(repr(e), traceback.format_exc())
        else:
            result, err = await self.loop.run_in_executor(
                self._exec_pool, self._call_user_fn, method, args, kwargs, spec)
        self._trace_task(spec, spec.method_name, t0, err)
        return await self._pack_results(spec, result, err, transport=transport)

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        try:
            from ray_tpu.util import tracing

            if tracing.enabled():
                tracing.flush()
        except Exception:
            pass

        async def _close():
            for pool in self._lease_cache.values():
                for lease in pool.idle:
                    await self._drop_lease(lease)
                pool.idle.clear()
            if self.server:
                await self.server.stop()
            if self.gcs:
                await self.gcs.close()
            for c in list(self._raylet_clients.values()) + list(self._worker_clients.values()):
                await c.close()
            if self.raylet:
                await self.raylet.close()

        try:
            self._run(_close(), timeout=10.0)
        except Exception:
            pass
        if self._owned_loop:
            self.loop.call_soon_threadsafe(self.loop.stop)
            if self._loop_thread:
                self._loop_thread.join(timeout=5.0)
        self.segments.clear()


# ---------------------------------------------------------------------------
# driver bootstrap
# ---------------------------------------------------------------------------


class DriverWorker(CoreWorker):
    """Driver facade: also owns the locally-started cluster, if any."""

    def __init__(self, *args, node_supervisor=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.node_supervisor = node_supervisor
        self.current_task_id = None
        self.current_actor_id = None

    def shutdown(self):
        super().shutdown()
        if self.node_supervisor is not None:
            self.node_supervisor.stop()
            self.node_supervisor = None


def connect_driver(address, num_cpus, num_tpus, resources, labels, namespace,
                   object_store_memory, log_to_driver,
                   include_dashboard=False, dashboard_port=None):
    supervisor = None
    dashboard_address = ""
    if address is None:
        from ray_tpu._private.node import NodeSupervisor

        node_res = dict(resources or {})
        if num_cpus is not None:
            node_res["CPU"] = float(num_cpus)
        if num_tpus is not None:
            node_res["TPU"] = float(num_tpus)
        supervisor = NodeSupervisor(resources=node_res, labels=labels,
                                    object_store_memory=object_store_memory)
        address = supervisor.start_head()
        if include_dashboard:
            dashboard_address = supervisor.start_dashboard(port=dashboard_port)
            logger.info("dashboard at http://%s", dashboard_address)
    elif include_dashboard:
        logger.warning(
            "include_dashboard=True is ignored when connecting to an "
            "existing cluster (%s); start one on the head node with "
            "`ray-tpu start --include-dashboard` instead", address)
    worker = DriverWorker(
        gcs_address=address,
        raylet_address=None,
        node_id=None,
        is_driver=True,
        namespace=namespace,
        node_supervisor=supervisor,
    )
    worker.dashboard_address = dashboard_address
    worker.log_to_driver = bool(log_to_driver)
    worker.connect()
    return worker

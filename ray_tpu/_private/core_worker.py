"""CoreWorker: the in-process runtime embedded in every driver and worker.

Reference: ``src/ray/core_worker`` — task submission with lease-then-push
(``task_submission/normal_task_submitter.cc:32``, lease reuse per scheduling
key), actor task submission with per-caller ordered queues
(``actor_task_submitter.cc``), task execution (``task_receiver.cc``), the
in-memory store for small results, the plasma provider for large ones, task
retries + lineage (``task_manager.cc``), and the gRPC service
(``HandlePushTask`` core_worker.cc:3360).

Round-1 deviations (documented; see SURVEY.md §7 hard parts):
- distributed refcounting is deferred: objects are freed explicitly or when
  the owning job exits (the store's LRU spill bounds memory meanwhile);
- object locations resolve via the GCS directory plus a direct owner fetch
  for small objects, rather than the reference's ownership directory.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import pickle

from ray_tpu._private import wire
import threading
import time
import traceback
import uuid
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import task_events
from ray_tpu._private.async_util import spawn
from ray_tpu._private.common import ActorOptions, TaskOptions, TaskSpec
from ray_tpu._private.config import RAY_CONFIG
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_store import SegmentCache, pack_blob, plan_layout, read_blob, write_blob, ShmSegment
from ray_tpu._private.reference_counter import ReferenceCounter
from ray_tpu._private.rpc import (
    RpcApplicationError,
    RpcError,
    RpcServer,
    RetryingRpcClient,
)
from ray_tpu._private.serialization import deserialize, loads_trusted, serialize
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    TaskError,
)
from ray_tpu.object_ref import ObjectRef

logger = logging.getLogger("ray_tpu.worker")

_LEASE_IDLE_S = 2.0

# cluster-unique metrics key tag (pids collide across nodes/restarts).
# Computed lazily AND per-pid: zygote-forked workers inherit this module
# already imported, so an import-time constant would make every forked
# worker publish to the same KV key, clobbering each other's metrics.
# Lock-guarded: the auto-flush loop and a manual publish_metrics() can race
# the first computation, and two tags for one process double-counts it.
_obs_proc_tag_cache: Optional[Tuple[int, str]] = None
_obs_proc_tag_lock = threading.Lock()


def _obs_proc_tag() -> str:
    global _obs_proc_tag_cache
    with _obs_proc_tag_lock:
        if _obs_proc_tag_cache is None \
                or _obs_proc_tag_cache[0] != os.getpid():
            _obs_proc_tag_cache = (os.getpid(), uuid.uuid4().hex[:10])
        return _obs_proc_tag_cache[1]

_LATENCY_BOUNDS = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0]

_obs_instruments = None


def _obs():
    """Built-in always-on instruments (reference: the core worker's
    ray_task_* opencensus metrics in metric_defs.cc), created lazily so
    merely importing this module registers nothing."""
    global _obs_instruments
    if _obs_instruments is None:
        from ray_tpu.util.metrics import Gauge, Histogram

        _obs_instruments = {
            "e2e": Histogram(
                "ray_tpu_task_e2e_seconds",
                "end-to-end task latency: submit to completion at the owner",
                boundaries=_LATENCY_BOUNDS, tag_keys=("function",)),
            "exec": Histogram(
                "ray_tpu_task_exec_seconds",
                "task execution latency on the worker",
                boundaries=_LATENCY_BOUNDS, tag_keys=("function",)),
            "loop_lag": Gauge(
                "ray_tpu_event_loop_lag_seconds",
                "io event-loop scheduling delay (sleep-drift sampled)"),
        }
    return _obs_instruments


def _task_span_id(spec: TaskSpec) -> str:
    """Deterministic execution-span id: the submitter never learns it, yet
    children submitted DURING execution and the span recorded AFTER it must
    agree on the id (retries get distinct spans per attempt)."""
    return f"{spec.task_id.hex()[:12]}a{spec.attempt}"


def _freeze(d: Dict[str, float]) -> tuple:
    return tuple(sorted(d.items()))


class _ActorView:
    """Owner-side view of one actor (reference: actor_task_submitter.cc)."""

    def __init__(self, actor_id: ActorID):
        self.actor_id = actor_id
        self.state = "PENDING_CREATION"
        self.address = ""
        self.seqno = 0
        self.client: Optional[RetryingRpcClient] = None
        self.state_changed = asyncio.Event()
        self.max_task_retries = 0
        self.death_cause = ""


class _LeasePool:
    """Per-scheduling-key task queue + worker lease pool (reference: the
    SchedulingKey queues in normal_task_submitter.cc — pipelined lease
    requests capped at max_pending_lease_requests, granted workers reused
    for queued tasks of the same shape, returned after an idle timeout).

    Throughput design for the asyncio tier: one *pusher* coroutine per
    granted lease pops queued task records and ships them in BATCHES over a
    single ``PushTaskBatch`` RPC, amortizing the per-call framing/event-loop
    overhead that otherwise dominates small-task throughput."""

    BATCH = 16  # base batch per push round trip (also the lease-count unit)
    BATCH_MAX = 128  # queue-depth-scaled ceiling (see _batch_cap)
    BATCH_MAX_BYTES = 1 << 20  # serialized-arg byte bound per push

    def __init__(self, core: "CoreWorker", key, opts, resources):
        from collections import deque

        self.core = core
        self.key = key
        self.opts = opts
        self.resources = resources
        self.pending = deque()  # task records awaiting a pusher
        self.pushers = 0
        self.active_leases = 0  # pushers currently holding a granted lease
        self.busy = 0  # pushers blocked inside a PushTaskBatch round trip
        self._work = asyncio.Event()  # set while pending is non-empty
        # owner-side lease cache: extra grants from a batched
        # RequestWorkerLease reply, consumed by sibling pushers without
        # another raylet round trip (dropped when the queue drains)
        self.spare_grants = deque()
        # grants currently being asked for by in-flight lease RPCs: without
        # this, N concurrent pushers each request the full batch for the
        # same queue and the raylet over-grants N-fold
        self.requesting = 0
        # EWMA of the push round trip, feeding the micro-batch hold-off
        # (see _pusher): long RTTs earn proportionally longer accumulation.
        # rtt_measured gates the short-task regime below: until a round
        # trip has actually completed, the pool could be running hour-long
        # tasks and must keep the conservative share division.
        self.rtt_ewma = 0.005
        self.rtt_measured = False
        # burst detector: consecutive submits with sub-300µs inter-arrival
        # (a `.remote()` loop runs at ~10µs-100µs/call; chains and trickle
        # traffic arrive at >= one push RTT apart and never trip this)
        self._burst_n = 0
        self._last_submit = 0.0

    def submit(self, record: dict):
        now = time.monotonic()
        if now - self._last_submit < 0.0003:
            self._burst_n += 1
        else:
            self._burst_n = 0
        self._last_submit = now
        self.pending.append(record)
        self._work.set()
        self._ensure_pushers()

    def _batch_cap(self) -> int:
        """Queue-depth-adaptive batch size (same spirit as plan_buckets:
        amortize per-item overhead into per-batch overhead up to a bound):
        deep backlogs earn bigger batches so a 20k-task burst pays ~1/128th
        of the per-push framing, while shallow queues keep small batches —
        one push can't hold the lease hostage. The byte bound is applied by
        the pusher while it pops (args ride the push payload)."""
        return min(self.BATCH_MAX, max(self.BATCH, len(self.pending) // 8))

    def _ensure_pushers(self):
        cap = RAY_CONFIG.max_pending_lease_requests
        # one AVAILABLE pusher per pending task up to the cap (reference:
        # pipelined lease requests in normal_task_submitter.cc) —
        # parallelism first; pushers blocked mid-push on a long task don't
        # count, or staggered long-task arrivals would serialize behind
        # them. Tiny tasks still batch because whichever pusher is granted
        # first drains a share of the queue per round trip.
        want = min(max(1, len(self.pending)), cap)
        while self.pushers - self.busy < want:
            self.pushers += 1
            spawn(self._pusher(), what="lease-pool pusher")

    async def _pusher(self):
        """Acquire one lease, then drain the queue in batches until idle."""
        try:
            try:
                lease = await self._do_request()
                if lease is None:
                    return  # queue drained by concurrent pushers
            except Exception as e:
                # a lease is unobtainable — and since busy nodes are waited
                # out (not errored), this means the shape stayed infeasible
                # for the whole window (or every raylet was unreachable).
                # If no sibling pusher holds a working lease, fail everything
                # queued NOW with the scheduling error; with a live lease the
                # failure is node-local (e.g. one raylet's stale PG view) and
                # the healthy pushers keep draining the queue.
                if self.active_leases == 0:
                    tb = traceback.format_exc()
                    while self.pending:
                        record = self.pending.popleft()
                        self.core._complete_error(record, TaskError(
                            f"scheduling failed for {record['name']}: {e}", tb))
                elif self.pending:
                    # a sibling lease survives, so queued tasks will drain
                    # onto it eventually — don't error them, but don't be
                    # silent either: the shape is currently unschedulable
                    # anywhere else (reference: infeasible-task warnings in
                    # cluster_task_manager.cc)
                    logger.warning(
                        "cannot acquire another lease for %s (%s); %d queued "
                        "task(s) remain behind %d existing lease(s)",
                        self.key, e, len(self.pending), self.active_leases)
                return
            idle_deadline = None
            self.active_leases += 1
            try:
                while True:
                    # divide the queue across ALL pushers (not just granted
                    # leases): soon-to-be-granted pushers must find work
                    # left, or long tasks serialize onto the first lease.
                    # On a saturated cluster this degrades to small batches,
                    # where push round trips are not the bottleneck anyway.
                    if len(self.pending) < self.BATCH and self._burst_n >= 4:
                        # Nagle-style micro-batching: the submit stream is
                        # BURSTING (consecutive sub-300µs inter-arrivals —
                        # a `.remote()` loop), so new arrivals can afford
                        # to accumulate for a fraction of the push round
                        # trip instead of paying a whole push per task.
                        # Chains and trickle traffic arrive >= one RTT
                        # apart, never trip the detector, and keep their
                        # first-push latency untouched.
                        deadline = time.monotonic() + min(
                            0.008, max(0.001, self.rtt_ewma / 4))
                        last = len(self.pending)
                        while last < self.BATCH \
                                and time.monotonic() < deadline:
                            await asyncio.sleep(0.001)
                            if len(self.pending) == last:
                                break  # burst ended; stop paying latency
                            last = len(self.pending)
                        # fall through: an empty queue parks below as usual
                    share = -(-len(self.pending) // max(1, self.pushers))
                    if self.rtt_measured and self.rtt_ewma < 0.1:
                        # short-task regime (sub-100ms push round trips):
                        # batch aggressively instead of dividing the queue
                        # across every live pusher — under a burst dozens
                        # of pushers are mid-flight, the share pins at 1-2
                        # and per-push framing dominates the owner loop.
                        # Long-task pools keep the share division so
                        # staggered arrivals don't serialize onto one
                        # lease (there the round trip IS the task).
                        share = len(self.pending)
                    take = max(1, min(self._batch_cap(), share))
                    batch = []
                    nbytes = 0
                    while self.pending and len(batch) < take:
                        r = self.pending.popleft()
                        batch.append(r)
                        nbytes += r.get("bytes", 0)
                        if nbytes >= self.BATCH_MAX_BYTES:
                            break
                    if not batch:
                        self._work.clear()
                        if self.pending:  # a submit raced the clear
                            continue
                        if idle_deadline is None:
                            idle_deadline = time.monotonic() + _LEASE_IDLE_S
                        remaining = idle_deadline - time.monotonic()
                        if remaining <= 0:
                            await self.core._drop_lease(lease)
                            return
                        try:
                            await asyncio.wait_for(self._work.wait(), remaining)
                        except asyncio.TimeoutError:
                            pass
                        continue
                    idle_deadline = None
                    self.busy += 1
                    try:
                        ok = await self._push_batch(lease, batch)
                    except Exception as e:
                        # a non-RPC failure (encoding bug, cancelled loop):
                        # deterministic, so retrying would loop — fail the
                        # batch loudly instead of stranding its futures
                        tb = traceback.format_exc()
                        for record in batch:
                            self.core._complete_error(record, TaskError(
                                f"task submission failed for "
                                f"{record['name']}: {e}", tb))
                        await self.core._drop_lease(lease)
                        return
                    finally:
                        self.busy -= 1
                    if not ok:
                        await self.core._drop_lease(lease)
                        return
            finally:
                self.active_leases -= 1
        finally:
            self.pushers -= 1
            if self.pending:
                self._work.set()
                self._ensure_pushers()
            elif self.pushers == 0:
                self._drop_spares()

    def _drop_spares(self):
        """Return cached-but-unused grants to their raylets (the queue
        drained before any pusher needed them)."""
        while self.spare_grants:
            spawn(self.core._drop_lease(self.spare_grants.popleft()),
                  what="spare-lease return")

    def _desired_count(self) -> int:
        """How many leases this request should ask for in one round trip:
        enough pushers to drain the queue a batch each, minus grants
        already held, cached, or being requested by sibling pushers,
        capped by the raylet's multi-grant bound."""
        want = -(-len(self.pending) // self.BATCH)
        want = min(want, RAY_CONFIG.max_pending_lease_requests)
        have = self.active_leases + len(self.spare_grants) + self.requesting
        return max(1, min(RAY_CONFIG.lease_max_grants, want - have))

    def _stash_extras(self, reply: dict, raylet_address: str):
        for g in reply.get("extra_grants") or ():
            self.spare_grants.append({
                "key": self.key, "lease_id": g["lease_id"],
                "worker_address": g["worker_address"],
                "raylet_address": raylet_address,
                "last_used": time.monotonic()})
        if self.spare_grants and self.pending:
            self._ensure_pushers()

    async def _push_batch(self, lease: dict, batch: List[dict]) -> bool:
        """Ship a batch to the leased worker. Returns False if the lease
        died (records are retried/failed individually)."""
        from ray_tpu.exceptions import TaskCancelledError

        core = self.core
        batch = [r for r in batch if not self._drop_if_cancelled(r)]
        if not batch:
            return True
        events_on = task_events.enabled()
        for record in batch:
            record["epoch"] = record.get("epoch", -1) + 1
            record["spec"].attempt = record["epoch"]
            record["_pushed_to"] = lease["worker_address"]
            if events_on:
                task_events.record(
                    record["spec"].task_id.hex(), task_events.SCHEDULED,
                    attempt=record["epoch"],
                    worker=lease["worker_address"],
                    job_id=record.get("_job_hex", ""))
        # template-aware framing: records from the submit warm path carry a
        # preserialized spec template blob — ship each distinct template
        # ONCE per batch plus (task_id, args, attempt) triples, instead of
        # re-encoding every full spec (options, selectors, runtime env)
        templates: List[bytes] = []
        tmpl_index: Dict[int, int] = {}
        items: List[tuple] = []
        for r in batch:
            tmpl = r.get("_tmpl")
            if tmpl is None:
                items.append(("s", r["spec"]))
            else:
                ix = tmpl_index.get(id(tmpl))
                if ix is None:
                    ix = tmpl_index[id(tmpl)] = len(templates)
                    templates.append(tmpl)
                spec = r["spec"]
                items.append(("t", ix, spec.task_id, spec.args_blob,
                              spec.attempt))
        payload = wire.dumps({"templates": templates, "items": items})
        stats = core._submit_stats
        stats["push_batches"] += 1
        stats["push_tasks"] += len(batch)
        push_t0 = time.perf_counter()
        try:
            reply = wire.loads(await core._worker_client(
                lease["worker_address"]).call(
                    "PushTaskBatch", payload, timeout=86400.0, retries=0))
        except (RpcError, asyncio.TimeoutError, OSError) as e:
            stats["push_s"] += time.perf_counter() - push_t0
            # requeue retriable records FIRST: the OOM probe below can take
            # seconds against a dead raylet and is only needed when some
            # record is about to surface a terminal error
            exhausted = []
            for record in batch:
                if record.get("_cancelled"):
                    # force-cancel kills the worker: deliver the
                    # cancellation, never a retry
                    core._complete_error(record, TaskCancelledError())
                    continue
                record["attempts"] += 1
                if record["attempts"] > record["max_retries"]:
                    exhausted.append(record)
                else:
                    logger.warning("retrying task %s (attempt %d): %s",
                                   record["name"], record["attempts"], e)
                    task_events.record(
                        record["spec"].task_id.hex(), task_events.RETRYING,
                        attempt=record["attempts"], error=f"worker died: {e}",
                        job_id=record.get("_job_hex", ""))
                    self._reset_stream_for_retry(record)
                    self.pending.append(record)
            if exhausted:
                oom = await self._was_oom(lease)
                for record in exhausted:
                    if oom:
                        core._complete_error(record, OutOfMemoryError(
                            f"worker running {record['name']} was killed by "
                            f"the node memory monitor (after "
                            f"{record['attempts']} attempts)", ""))
                    else:
                        core._complete_error(record, TaskError(
                            f"worker died running {record['name']} "
                            f"(after {record['attempts']} attempts): {e}", ""))
            return False
        rtt = time.perf_counter() - push_t0
        stats["push_s"] += rtt
        self.rtt_ewma = 0.8 * self.rtt_ewma + 0.2 * rtt
        self.rtt_measured = True
        for record, res in zip(batch, reply["results"]):
            if res["status"] == "ok":
                core._process_reply_refs(res, lease["worker_address"])
                core._complete_ok(record, res["results"],
                                  stream_count=res.get("stream_count"))
            else:
                err: TaskError = loads_trusted(res["error"])
                opts = record["spec"].options
                from ray_tpu.exceptions import StrayInterrupt

                stray = isinstance(getattr(err, "cause", None), StrayInterrupt)
                if (opts.retry_exceptions or stray) \
                        and not isinstance(err, TaskCancelledError) \
                        and record["attempts"] < record["max_retries"]:
                    record["attempts"] += 1
                    task_events.record(
                        record["spec"].task_id.hex(), task_events.RETRYING,
                        attempt=record["attempts"], error=str(err),
                        job_id=record.get("_job_hex", ""))
                    self._reset_stream_for_retry(record)
                    self.pending.append(record)
                else:
                    core._complete_error(record, err)
        return True

    def _reset_stream_for_retry(self, record: dict):
        """A retried streaming task replays from index 0 under a new
        attempt: unconsumed indices must wait for the retry's values
        instead of serving a dead attempt's partial output."""
        if record["spec"].num_returns != -1:
            return
        st = self.core._streams.get(record["spec"].task_id.binary())
        if st is not None:
            st["produced"] = 0

    def _drop_if_cancelled(self, record: dict) -> bool:
        if not record.get("_cancelled"):
            return False
        from ray_tpu.exceptions import TaskCancelledError

        self.core._complete_error(record, TaskCancelledError())
        return True

    async def _was_oom(self, lease: dict) -> bool:
        """After a push failure, ask the granting raylet whether the memory
        monitor killed the worker (surfaces OutOfMemoryError to the user)."""
        try:
            reply = wire.loads(await self.core._raylet_client(
                lease["raylet_address"]).call(
                    "WasWorkerOOM", wire.dumps(
                        {"worker_address": lease["worker_address"]}),
                    timeout=5.0, retries=0))
            return bool(reply.get("oom"))
        except (RpcError, asyncio.TimeoutError, OSError):
            return False

    async def _do_request(self) -> Optional[dict]:
        """Acquire one lease. Busy nodes are waited out for as long as the
        shape stays feasible-by-totals (the reference queues leases at the
        raylet, cluster_lease_manager.cc — a saturated cluster must queue,
        not error); only a shape no node can EVER satisfy (PickNode exhausts
        infeasible_task_timeout_s) or a cluster-wide unreachability raises.

        Two-level fast path (reference: lease_policy.cc + raylet
        spillback): plain leases go straight to the LOCAL raylet, which
        grants or redirects via its synced resource view — no GCS round
        trip. PG- and strategy-pinned leases, and the infeasible fallback
        (which records autoscaler demand), resolve through GCS PickNode."""
        if self.spare_grants:
            # owner-side lease cache: a sibling's batched request already
            # granted a worker for this key — adopt it, zero round trips
            return self.spare_grants.popleft()
        opts, resources = self.opts, self.resources
        req = {
            "resources": resources,
            "label_selector": opts.label_selector,
            "job_id": self.core.job_id,
            "pg": opts.placement_group.id.binary() if opts.placement_group else None,
            "bundle_index": opts.placement_group_bundle_index,
            "runtime_env": opts.runtime_env,
        }
        if opts.placement_group is None and opts.scheduling_strategy is None:
            start_addr = None
            if self.pending:
                # locality-aware lease targeting: start the chain at the
                # raylet holding the most argument bytes, so the task runs
                # next to its data instead of pulling it (reference:
                # lease_policy.cc). Spillback tie-breaks on the same map.
                loc, start_addr = await self.core._arg_locality(
                    self.pending[0])
                if loc:
                    req = dict(req, locality=loc)
            out = await self._request_two_level(req, start_addr)
            if out != "fallback":
                return out  # a lease, or None (queue drained: stand down)
            # cluster-wide infeasible / local raylet gone: fall through to
            # the GCS path, which records demand (autoscaler) and waits
            # out the infeasible window
        node = await self.core._pick_node(opts, resources)
        if node is None:
            raise RuntimeError(f"no feasible node for resources={resources} "
                               f"selector={opts.label_selector}")
        raylet = self.core._raylet_client(node["address"])
        unreachable_deadline = None
        infeasible_since = None
        busy_delay = 0.1
        while True:
            if not self.pending:
                # the queue drained while we were acquiring (other pushers
                # served it): stand down instead of spinning and emitting
                # phantom autoscaler demand for work that no longer exists
                return None
            try:
                req["count"] = n = self._desired_count()
                self.requesting += n
                try:
                    reply = wire.loads(await raylet.call(
                        "RequestWorkerLease", wire.dumps(req),
                        timeout=RAY_CONFIG.worker_start_timeout_s + 30,
                        connect_timeout=5.0, retries=1))
                finally:
                    self.requesting -= n
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                # raylet unreachable (node died between pick and lease):
                # re-pick a node until the GCS view catches up
                if unreachable_deadline is None:
                    unreachable_deadline = (
                        time.monotonic() + RAY_CONFIG.worker_start_timeout_s * 4)
                if time.monotonic() > unreachable_deadline:
                    raise RuntimeError(f"lease request kept failing: {e}")
                await asyncio.sleep(0.5)
                node2 = await self.core._pick_node(opts, resources)
                if node2 is not None:
                    node = node2
                    raylet = self.core._raylet_client(node["address"])
                continue
            unreachable_deadline = None
            if reply["status"] == "runtime_env_failed":
                raise RuntimeError(
                    f"runtime_env setup failed: {reply.get('error', '')}")
            if reply["status"] == "granted":
                self._stash_extras(reply, node["address"])
                return {"key": self.key, "lease_id": reply["lease_id"],
                        "worker_address": reply["worker_address"],
                        "raylet_address": node["address"],
                        "last_used": time.monotonic()}
            if reply["status"] == "pg_removed":
                # the raylet has no live reserve for this group. Confirm
                # against GCS truth before failing: a stale raylet view
                # (restart, mid-reschedule) must retry bounded like
                # "infeasible", while a genuine removal fails queued tasks
                # now (reference: tasks routed to a removed PG error, they
                # never reroute to node capacity)
                pg_info = None
                try:
                    pg_info = (await self.core._gcs_call(
                        "GetPlacementGroup",
                        {"pg_id": req["pg"]}))["info"]
                except (RpcError, asyncio.TimeoutError, OSError) as e:
                    logger.debug("GetPlacementGroup(%s) failed; treating "
                                 "PG as gone: %s", req["pg"], e)
                if pg_info is None or pg_info.get("state") == "REMOVED":
                    raise RuntimeError(
                        "placement group was removed; queued tasks against "
                        "its bundles cannot be scheduled")
                if infeasible_since is None:
                    infeasible_since = time.monotonic()
                elif time.monotonic() - infeasible_since > \
                        RAY_CONFIG.infeasible_task_timeout_s:
                    raise RuntimeError(
                        "raylet persistently reports no reserve for a live "
                        "placement group (stale bundle view?)")
                # re-pick: a reschedule may have moved the bundle
                node2 = await self.core._pick_node(opts, resources)
                if node2 is not None:
                    node = node2
                    raylet = self.core._raylet_client(node["address"])
                await asyncio.sleep(busy_delay)
                busy_delay = min(busy_delay * 1.5, 2.0)
                continue
            if reply["status"] == "infeasible":
                # the raylet's totals reject a shape the GCS view accepts
                # (e.g. stale PG bundle after a raylet restart): bounded —
                # a permanent disagreement must error, not loop forever
                if infeasible_since is None:
                    infeasible_since = time.monotonic()
                elif time.monotonic() - infeasible_since > \
                        RAY_CONFIG.infeasible_task_timeout_s:
                    raise RuntimeError(
                        f"raylet reports resources={resources} infeasible")
            else:
                infeasible_since = None
            if reply["status"] in ("busy", "infeasible", "infeasible_cluster"):
                # re-pick; a transient None (PG/affinity nodes briefly
                # absent from the GCS view) keeps the current raylet —
                # persistent disagreement is bounded by infeasible_since.
                # Backoff: saturation can last hours; 16 pushers polling at
                # 10 Hz each would hammer the GCS for nothing (the raylet
                # lease call itself already parks ~worker_start_timeout_s)
                node2 = await self.core._pick_node(opts, resources)
                if node2 is not None and node2["address"] != node["address"]:
                    node = node2
                    raylet = self.core._raylet_client(node["address"])
                await asyncio.sleep(busy_delay)
                busy_delay = min(busy_delay * 1.5, 2.0)
            else:
                busy_delay = 0.1

    async def _request_two_level(self, base_req: dict,
                                 start_addr: Optional[str] = None):
        """Lease via the local raylet + spillback chain (reference:
        normal_task_submitter going to the lease policy's raylet, raylet
        spillback at cluster_lease_manager.cc:421). Returns a lease dict,
        None when the queue drained (stand down), or "fallback" when the
        cluster has no feasible node / the local raylet is unreachable —
        the caller then uses the GCS path, which records autoscaler demand."""
        core = self.core
        addr = start_addr or core.raylet_address
        req = dict(base_req, allow_spillback=True)
        max_hops = RAY_CONFIG.lease_spillback_max_hops
        hops = 0
        unreachable = 0
        busy_delay = 0.1
        while True:
            if not self.pending:
                return None
            if self.spare_grants:
                return self.spare_grants.popleft()
            try:
                req["count"] = n = self._desired_count()
                self.requesting += n
                try:
                    reply = wire.loads(await core._raylet_client(addr).call(
                        "RequestWorkerLease", wire.dumps(req),
                        timeout=RAY_CONFIG.worker_start_timeout_s + 30,
                        connect_timeout=5.0, retries=1))
                finally:
                    self.requesting -= n
            except (RpcError, asyncio.TimeoutError, OSError):
                unreachable += 1
                if addr != core.raylet_address:
                    # the spill target died mid-chain: restart locally
                    addr = core.raylet_address
                    hops = 0
                    continue
                if unreachable >= 6:
                    return "fallback"  # local raylet gone: let GCS decide
                await asyncio.sleep(0.5)
                continue
            unreachable = 0
            status = reply["status"]
            if status == "runtime_env_failed":
                raise RuntimeError(
                    f"runtime_env setup failed: {reply.get('error', '')}")
            if status == "granted":
                self._stash_extras(reply, addr)
                return {"key": self.key, "lease_id": reply["lease_id"],
                        "worker_address": reply["worker_address"],
                        "raylet_address": addr,
                        "last_used": time.monotonic()}
            if status == "spillback":
                hops += 1
                addr = reply["retry_at"]
                if hops >= max_hops:
                    # stop chasing: park at the hop-limit raylet (its local
                    # queue serves us when capacity frees)
                    req["allow_spillback"] = False
                continue
            if status == "busy":
                # parked a full window without a grant: views may have
                # changed — re-enable spillback and keep queueing
                hops = 0
                req["allow_spillback"] = True
                await asyncio.sleep(busy_delay)
                busy_delay = min(busy_delay * 1.5, 2.0)
                continue
            # "infeasible" / "infeasible_cluster" / unknown
            return "fallback"


class CoreWorker:
    """One instance per process; drives all cluster interaction."""

    mode = "cluster"

    def __init__(
        self,
        gcs_address: str,
        raylet_address: Optional[str],
        node_id: Optional[NodeID],
        is_driver: bool,
        namespace: str = "default",
        loop: Optional[asyncio.AbstractEventLoop] = None,
        session_dir: str = "",
    ):
        self.gcs_address = gcs_address
        self.raylet_address = raylet_address
        # owner-resident object directory (locations of objects this
        # worker owns, fed by raylet seal announcements)
        self._obj_locations: Dict[bytes, dict] = {}
        self.node_id = node_id
        self.is_driver = is_driver
        self.namespace = namespace
        self.worker_id = WorkerID.from_random()
        self.session_dir = session_dir
        self.job_id: JobID = JobID.nil()
        self._owned_loop = loop is None
        self.loop = loop or asyncio.new_event_loop()
        self._loop_thread: Optional[threading.Thread] = None
        self.server: Optional[RpcServer] = None
        self.address = ""
        self.gcs: Optional[RetryingRpcClient] = None
        self.raylet: Optional[RetryingRpcClient] = None
        self._raylet_clients: Dict[str, RetryingRpcClient] = {}
        self._worker_clients: Dict[str, RetryingRpcClient] = {}
        # owner state
        self.memory_store: Dict[ObjectID, Any] = {}
        self._result_futures: Dict[ObjectID, asyncio.Future] = {}
        # return ids whose producing task is in flight but whose result
        # future has not been demanded yet: futures are allocated lazily on
        # the first get/await (submit only marks pendency — a dict insert)
        self._pending_returns: Dict[ObjectID, bool] = {}
        self._in_store: Dict[ObjectID, bool] = {}
        self._tasks: Dict[TaskID, dict] = {}  # lineage / retry records
        self._actor_inflight: Dict[TaskID, dict] = {}  # for cancel()
        self._lineage_bytes = 0
        # ownership refcounting (reference: reference_counter.h:44)
        self.ref_counter = ReferenceCounter(lambda: self.address)
        self._free_pending: set = set()
        # batched zero-ref intake: __del__-side ref drops append here (a
        # GIL-atomic deque op) and the 0.2s refcount sweep drains it —
        # replacing a per-object call_soon_threadsafe self-pipe write,
        # which at 20k frees/s was a visible slice of the io loop
        from collections import deque as _fdeque

        self._free_zero_q: "Any" = _fdeque()
        self._free_grace_q: "Any" = _fdeque()  # (deadline, oid) FIFO
        # owner-initiated borrow tracking (reference: WaitForRefRemoved in
        # reference_counter.cc): per borrower address, {oid: generation}
        # being watched by a long-poll loop — the generation fences stale
        # done-replies against concurrent re-registrations
        self._borrow_watch_sets: Dict[str, Dict[bytes, int]] = {}
        self._borrow_watch_active: set = set()
        self._lease_cache: Dict[tuple, List[dict]] = {}
        self._renv_prepared: Dict[str, dict] = {}
        self.job_runtime_env: Optional[dict] = None
        self._actors: Dict[ActorID, _ActorView] = {}
        self._actor_name_cache: Dict[ActorID, tuple] = {}
        self._pushed_functions: set = set()
        self._fn_key_cache: Dict[int, tuple] = {}
        # submit fast path (reference: the owner hot loop in
        # normal_task_submitter.cc): per-RemoteFunction cache of the
        # preserialized TaskSpec template (everything invariant across
        # `.remote()` calls of one function+options pair), the resolved
        # function key / prepared options, and the lease pool — so a warm
        # submit fills only task_id + args instead of re-framing the whole
        # spec through wire.dumps. Keyed by id() WITH a strong ref (slot 0)
        # so a recycled id can never alias a different function.
        self._spec_template_cache: Dict[int, tuple] = {}
        # per-submit cost accounting (drives the STRESS_r* µs breakdown and
        # the fast-path regression tests); plain counters, no locks — all
        # writers hold the GIL per op and precision loss is acceptable
        self._submit_stats: Dict[str, float] = {
            "count": 0, "serialize_s": 0.0, "events_s": 0.0,
            "kickoff_s": 0.0, "push_s": 0.0, "push_tasks": 0,
            "push_batches": 0, "spec_frames": 0, "kickoff_wakeups": 0,
            "fast_path": 0, "pack_pool_hits": 0, "pack_pool_misses": 0,
            "wait_vector_polls": 0, "result_future_batches": 0,
            "result_futures_batched": 0}
        self._put_index = 0
        self._spread_hint = 0
        self.segments = SegmentCache()
        # executor state
        self._fn_cache: Dict[str, Any] = {}
        # cancellation: running task_id -> executing thread ident, plus
        # cancels that arrived before their task started, plus every tid a
        # cancel was requested for (stray async-exc detection)
        self._running_tasks: Dict[bytes, int] = {}
        self._running_async_tasks: Dict[bytes, asyncio.Task] = {}
        self._cancelled_pending: set = set()
        self._cancel_requested: set = set()
        # streaming generators: task_id -> {produced, total, error, event}
        # (reference: task_manager.cc dynamic return handling)
        self._streams: Dict[bytes, dict] = {}
        # cross-host channel mailboxes (reader-hosted; reference:
        # experimental_mutable_object_provider.cc cross-node channel legs):
        # name -> {"q": deque, "data": Event, "space": Event, "cap": int}
        self._chan_mail: Dict[str, dict] = {}
        self._chan_closed: set = set()  # torn-down mailboxes drop pushes
        self.actor_instance = None
        self.actor_id: Optional[ActorID] = None
        # device-object transport (reference: per-actor GPUObjectStore):
        # values produced by tensor_transport-marked methods stay here
        self.device_store: Dict[bytes, Any] = {}
        self._device_fetch_cache: Dict[bytes, Any] = {}
        self._actor_async = False
        self._exec_pool = None
        self._exec_lock = threading.Lock()
        # submit-side kickoff batching: one loop wakeup per BURST of
        # .remote() calls, not one per call (call_soon_threadsafe writes
        # the loop's self-pipe — ~50us each on a small host)
        from collections import deque as _deque

        self._kickoff_q: "Any" = _deque()
        self._kickoff_scheduled = False
        self._order_buf: Dict[str, dict] = {}
        self._tls = threading.local()
        self._shutdown = False
        self.node_hex = node_id.hex() if node_id else ""

    # ------------------------------------------------------------------
    # loop plumbing
    # ------------------------------------------------------------------

    def _queue_kickoff(self, fn):
        """Enqueue a submit-side continuation; ONE loop wakeup per burst of
        `.remote()` calls (call_soon_threadsafe writes the loop's self-pipe,
        ~50us each on a small host — per-task it would dominate the submit
        hot loop)."""
        self._kickoff_q.append(fn)
        if not self._kickoff_scheduled:
            self._kickoff_scheduled = True
            self._submit_stats["kickoff_wakeups"] += 1
            self.loop.call_soon_threadsafe(self._drain_kickoffs)

    def _drain_kickoffs(self):
        """Drain the whole queue, THEN clear the scheduled flag: submits
        landing mid-drain ride this drain instead of paying another
        self-pipe write. The post-clear recheck closes the race where a
        producer appended between our empty pop and the flag clear (it saw
        the flag still set and skipped its wakeup)."""
        while True:
            try:
                fn = self._kickoff_q.popleft()
            except IndexError:
                # raylint: disable=RCE001 benign-race flag protocol: the post-clear recheck below closes the lost-wakeup window (see docstring); a lock here would put the submit hot loop behind the drain
                self._kickoff_scheduled = False
                if self._kickoff_q:
                    self._kickoff_scheduled = True
                    self.loop.call_soon(self._drain_kickoffs)
                return
            try:
                fn()
            except Exception:
                logger.exception("task kickoff failed")

    def _return_pending(self, oid: ObjectID) -> bool:
        """Is a locally-owned task still producing this return id?"""
        if oid in self._pending_returns:
            return True
        fut = self._result_futures.get(oid)
        return fut is not None and not fut.done()

    def _ensure_result_future(self, oid: ObjectID):
        """Result future on demand (loop thread only): submit marks
        pendency in ``_pending_returns`` — a dict insert — and the FIRST
        get/await allocates the future. Tasks whose results are consumed
        via wait/stream/store paths never pay the per-submit allocation."""
        fut = self._result_futures.get(oid)
        if fut is None and oid in self._pending_returns:
            fut = self._result_futures[oid] = self.loop.create_future()
        return fut

    def _ensure_result_futures(self, oids: set) -> int:
        """Batched ``_ensure_result_future`` (loop thread only): ONE
        C-level set intersection against the pending-return index finds
        every ref whose future is demanded but unallocated, then one pass
        allocates them — the first wait()/get() poll over a k-ref window
        stops paying k separate dict-probe chains. Returns the number of
        futures created."""
        want = self._pending_returns.keys() & oids
        created = 0
        for oid in want:
            if oid not in self._result_futures:
                self._result_futures[oid] = self.loop.create_future()
                created += 1
        if created:
            # raylint: disable=RCE001 plain diagnostic counters, deliberately unlocked (see _submit_stats init): each += is one dict-slot RMW under the GIL and a lost increment only skews a stat
            self._submit_stats["result_future_batches"] += 1
            self._submit_stats["result_futures_batched"] += created
        return created

    def _start_loop(self):
        if self._loop_thread is not None or not self._owned_loop:
            return
        self._loop_thread = threading.Thread(
            target=self.loop.run_forever, name="ray_tpu-io", daemon=True
        )
        self._loop_thread.start()

    def _run(self, coro, timeout=None):
        """Run a coroutine on the io loop from any user thread."""
        if threading.current_thread() is self._loop_thread:
            raise RuntimeError("blocking call on the io loop")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    # ------------------------------------------------------------------
    # connect
    # ------------------------------------------------------------------

    def connect(self):
        self._start_loop()
        self._run(self._connect())
        if RAY_CONFIG.distributed_refcounting:
            from ray_tpu import object_ref as object_ref_mod

            self.ref_counter.on_owned_zero = self._on_owned_zero
            self.ref_counter.on_borrow_first = self._on_borrow_first
            object_ref_mod.set_ref_counter(self.ref_counter)
            # periodic drain of the __del__-safe deletion queue (refs dropped
            # while the process is otherwise idle must still free)
            self._sweep_fut = asyncio.run_coroutine_threadsafe(
                self._refcount_sweep(), self.loop)
        # always-on observability: task-event flush + periodic metrics
        # publish + loop-lag sampling (reference: the core worker's
        # task_event_buffer flush timer + metrics agent push)
        self._obs_fut = asyncio.run_coroutine_threadsafe(
            self._obs_flush_loop(), self.loop)
        return self

    async def _obs_flush_loop(self):
        """Ship buffered task lifecycle events every
        ``task_events_flush_interval_s`` and auto-publish this process's
        metrics registry every ``metrics_flush_interval_s`` (replacing the
        manual ``publish_metrics()``). The sleep's drift doubles as the
        event-loop lag sample."""
        interval = RAY_CONFIG.task_events_flush_interval_s
        metrics_every = RAY_CONFIG.metrics_flush_interval_s
        last_metrics = 0.0
        while not self._shutdown:
            before = time.monotonic()
            await asyncio.sleep(interval)
            lag = max(0.0, time.monotonic() - before - interval)
            try:
                _obs()["loop_lag"].set(lag)
                events, dropped = task_events.drain()
                if events or dropped:
                    try:
                        await self._gcs_call("AddTaskEvents", {
                            "events": events, "dropped": dropped})
                    except (RpcError, asyncio.TimeoutError, OSError) as e:
                        task_events.rebuffer(events, dropped)
                        logger.debug("task-event flush failed "
                                     "(will retry): %s", e)
                now = time.monotonic()
                if now - last_metrics >= metrics_every:
                    last_metrics = now
                    await self._publish_metrics()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.debug("observability flush iteration failed",
                             exc_info=True)

    async def _publish_metrics(self):
        """Push this process's metrics registry to the GCS KV (metrics
        namespace); the dashboard's /metrics aggregates all processes.
        The goodput ledger rides the same cadence into ns="goodput" (and
        the flush itself is billed to the ledger's overhead bucket)."""
        from ray_tpu.util import goodput
        from ray_tpu.util.metrics import scrape_metrics

        t0 = time.perf_counter()
        # the ledger flush first: flush_payload() mirrors the derived
        # gauges onto the registry, so the scrape below carries them
        gp = goodput.flush_payload(node=self.node_hex)
        if gp is not None:
            try:
                await self._gcs_call("KVPut", {
                    "ns": "goodput", "key": f"proc_{_obs_proc_tag()}",
                    "value": wire.dumps(gp)})
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("goodput publish failed (will retry): %s", e)
        snap = scrape_metrics()
        if snap:
            payload = {"pid": os.getpid(), "time": time.time(),
                       "node": self.node_hex, "metrics": snap}
            try:
                await self._gcs_call("KVPut", {
                    "ns": "metrics", "key": f"proc_{_obs_proc_tag()}",
                    "value": wire.dumps(payload)})
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("metrics publish failed (will retry): %s", e)
        if gp is not None:
            # observability's own cost, attributed only while a ledger is
            # active (an idle util proc should not anchor one just for
            # its flush loop)
            goodput.add("overhead", time.perf_counter() - t0)

    async def _refcount_sweep(self):
        last_reassert = time.monotonic()
        while not self._shutdown:
            try:
                self.ref_counter.flush_deletes()
                while self._free_zero_q:
                    self._schedule_free(self._free_zero_q.popleft())
                now = time.monotonic()
                while self._free_grace_q and self._free_grace_q[0][0] <= now:
                    _, oid = self._free_grace_q.popleft()
                    spawn(self._free_owned(oid), what="owned-object free")
                if time.monotonic() - last_reassert > 30.0:
                    last_reassert = time.monotonic()
                    # fire-and-track: an unreachable owner (10s timeout
                    # each) must not stall the 0.2s flush cadence
                    spawn(self._reassert_borrows(), what="borrow re-assert")
            except Exception:
                logger.exception("refcount sweep failed")
            await asyncio.sleep(0.2)

    async def _reassert_borrows(self):
        """Periodically re-register still-held foreign borrows with their
        owners (bulk, idempotent): heals an owner that wrongly reclaimed a
        live borrower after a transient partition."""
        by_owner: Dict[str, list] = {}
        for oid, owner in self.ref_counter.borrowed_held():
            by_owner.setdefault(owner, []).append(oid)

        async def _one(owner, oids):
            try:
                await self._worker_client(owner).call(
                    "AddBorrowers", wire.dumps(
                        {"oids": oids, "address": self.address}),
                    timeout=10.0, retries=1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                # next sweep retries; the owner may simply be gone
                logger.debug("AddBorrowers to %s failed: %s", owner, e)

        # concurrent: one slow/dead owner must not delay re-asserts to the
        # reachable ones while their death-timeout clocks run
        await asyncio.gather(*[_one(o, oids) for o, oids in by_owner.items()])

    async def _connect(self):
        self.server = RpcServer(self._handle_rpc)
        self.address = await self.server.start()
        self.gcs = RetryingRpcClient(
            self.gcs_address, on_push=self._on_push, on_reconnect=self._on_gcs_reconnect
        )
        if self.is_driver:
            reply = wire.loads(await self.gcs.call("RegisterDriver", wire.dumps({
                "address": self.address,
                "namespace": self.namespace,
                "entrypoint": " ".join(os.sys.argv[:2]),
            })))
            self.job_id = JobID(reply["job_id"])
        channels = ["actors"]
        if self.is_driver and getattr(self, "log_to_driver", False):
            channels.append("logs")
        await self.gcs.call("Subscribe", wire.dumps({"channels": channels}))
        if self.raylet_address:
            self.raylet = RetryingRpcClient(self.raylet_address)
        else:
            # pick the head node's raylet as our local raylet
            nodes = wire.loads(await self.gcs.call("GetAllNodes", b""))["nodes"]
            head = next((n for n in nodes if n["is_head"]), nodes[0] if nodes else None)
            if head is None:
                raise RuntimeError("no raylets registered with the GCS")
            self.raylet_address = head["address"]
            self.node_hex = head["node_id"]
            self.raylet = RetryingRpcClient(self.raylet_address)

    async def _on_gcs_reconnect(self, client):
        try:
            channels = ["actors"]
            if self.is_driver and getattr(self, "log_to_driver", False):
                channels.append("logs")
            await client.call("Subscribe", wire.dumps({"channels": channels}))
        except Exception:
            logger.warning("GCS reconnect: re-subscribe failed", exc_info=True)
        if self.is_driver and not self.job_id.is_nil():
            # re-bind this connection to our job after a GCS restart so
            # driver-disconnect cleanup still fires (GCS FT)
            for _ in range(3):
                try:
                    await client.call("ReattachDriver", wire.dumps(
                        {"job_id": self.job_id.binary()}))
                    break
                except Exception:
                    logger.warning("GCS reconnect: ReattachDriver failed",
                                   exc_info=True)
                    await asyncio.sleep(0.2)

    def _on_push(self, channel: str, payload: bytes):
        msg = wire.loads(payload)
        if channel == "logs":
            import sys as _sys

            node = msg.get("node", "?")
            for line in msg.get("lines", []):
                print(f"\x1b[2m({node})\x1b[0m {line}", file=_sys.stderr)
            return
        if channel == "actors":
            info = msg.get("info", {})
            aid = ActorID.from_hex(info["actor_id"])
            view = self._actors.get(aid)
            if view is not None:
                if info["address"] != view.address:
                    view.seqno = 0  # new incarnation
                view.state = info["state"]
                view.address = info["address"]
                view.death_cause = info.get("death_cause", "")
                view.client = None
                ev, view.state_changed = view.state_changed, asyncio.Event()
                ev.set()

    # ------------------------------------------------------------------
    # clients
    # ------------------------------------------------------------------

    def _raylet_client(self, address: str) -> RetryingRpcClient:
        if address == self.raylet_address:
            return self.raylet
        c = self._raylet_clients.get(address)
        if c is None:
            c = RetryingRpcClient(address)
            self._raylet_clients[address] = c
        return c

    def _worker_client(self, address: str) -> RetryingRpcClient:
        c = self._worker_clients.get(address)
        if c is None:
            c = RetryingRpcClient(address)
            self._worker_clients[address] = c
        return c

    async def _gcs_call(self, method: str, req: dict, timeout=None) -> dict:
        return wire.loads(await self.gcs.call(method, wire.dumps(req), timeout=timeout))

    # ------------------------------------------------------------------
    # function / class table
    # ------------------------------------------------------------------

    async def _prepare_runtime_env(self, renv):
        """Normalize + upload runtime-env packages once (driver side;
        reference: runtime_env/working_dir.py upload + uri_cache.py)."""
        import json as _json

        from ray_tpu._private import runtime_env as renv_mod

        if renv is None:
            renv = getattr(self, "job_runtime_env", None)
        renv = renv_mod.normalize(renv)
        if not renv:
            return None
        cache_key = _json.dumps(renv, sort_keys=True)
        cached = self._renv_prepared.get(cache_key)
        if cached is not None:
            return cached
        out = dict(renv)

        async def upload(path):
            if isinstance(path, dict):  # already a KV reference
                return path
            sha, blob, base = renv_mod.package_dir(path)
            key = f"pkg:{sha}"
            reply = await self._gcs_call("KVGet", {"ns": "renv", "key": key})
            if reply["value"] is None:
                await self._gcs_call("KVPut", {"ns": "renv", "key": key,
                                               "value": blob})
            return {"kv_key": key, "sha": sha, "base": base}

        if "working_dir" in out:
            out["working_dir"] = await upload(out["working_dir"])
        if "py_modules" in out:
            out["py_modules"] = [await upload(p) for p in out["py_modules"]]
        self._renv_prepared[cache_key] = out
        return out

    async def _push_function(self, obj) -> str:
        cached = self._fn_key_cache.get(id(obj))
        if cached is not None and cached[0] is obj:
            return cached[1]
        blob = cloudpickle.dumps(obj)
        key = hashlib.sha1(blob).hexdigest()
        if key not in self._pushed_functions:
            await self._gcs_call("KVPut", {"ns": "fn", "key": key, "value": blob,
                                           "overwrite": False})
            self._pushed_functions.add(key)
        # keyed by identity WITH a strong ref so a recycled id can't alias
        self._fn_key_cache[id(obj)] = (obj, key)
        return key

    async def _fetch_function(self, key: str):
        fn = self._fn_cache.get(key)
        if fn is None:
            reply = await self._gcs_call("KVGet", {"ns": "fn", "key": key})
            if reply["value"] is None:
                raise RuntimeError(f"function {key} not found in GCS")
            fn = loads_trusted(reply["value"])
            self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    # objects: put / get / wait
    # ------------------------------------------------------------------

    def _next_put_id(self) -> ObjectID:
        self._put_index += 1
        base = TaskID(self.worker_id.binary()[: TaskID.SIZE - 4] + self.job_id.binary())
        return ObjectID.from_put(base, self._put_index % 0x7FFF)

    def put(self, value: Any) -> ObjectRef:
        oid = self._next_put_id()
        self._run(self._put_value(oid, value))
        return ObjectRef(oid, self.address)

    async def _put_value(self, oid: ObjectID, value: Any):
        from ray_tpu.object_ref import collect_serialized_refs

        with collect_serialized_refs() as inner:
            inband, buffers = serialize(value)
        total = len(inband) + sum(b.nbytes for b in buffers)
        if total < RAY_CONFIG.object_inline_max_bytes:
            self.memory_store[oid] = value
            return
        await self._store_blob(oid, inband, buffers)
        self._in_store[oid] = True
        # a stored blob holds refs only as bytes: pin them for its lifetime
        self.ref_counter.pin_nested(oid.binary(), inner)

    async def _store_blob(self, oid: ObjectID, inband: bytes, buffers,
                          attempt: int = 0, owner: str = ""):
        total, offsets = plan_layout(inband, buffers)
        reply = wire.loads(await self.raylet.call("StoreCreate", wire.dumps(
            {"oid": oid.binary(), "size": total, "attempt": attempt,
             "owner": owner or self.address})))
        if reply["status"] in ("exists", "stale_attempt"):
            # seal-once: the id is already (or about to be) bound to a value
            # for this or a newer execution epoch; this writer stands down
            return
        if reply["status"] != "ok":
            raise ObjectLostError(f"object store rejected {oid.hex()}: {reply}")
        if "arena_name" in reply:
            # native arena backend: write into the shared arena at the offset
            seg = self.segments.open(reply["arena_name"])
            off = reply["offset"]
            region = memoryview(seg.buf)[off : off + total]
            write_blob(region, inband, buffers, offsets)
        else:
            seg = ShmSegment(reply["shm_name"])
            try:
                write_blob(seg.buf, inband, buffers, offsets)
            finally:
                seg.close()
        await self.raylet.call("StoreSeal", wire.dumps(
            {"oid": oid.binary(), "attempt": attempt}))

    async def _read_local_store(self, oid: ObjectID, timeout: float, pull=True,
                                prio: int = 0, owner: str = ""):
        reply = wire.loads(await self.raylet.call("StoreGet", wire.dumps(
            {"oid": oid.binary(), "timeout": timeout, "pull": pull,
             "prio": prio, "owner": owner}),
            timeout=timeout + 10.0))
        status = reply["status"]
        if status == "inline":
            inband, buffers = read_blob(reply["blob"])
            return True, deserialize(inband, buffers)
        if status == "shm":
            seg = self.segments.open(reply["shm_name"])
            inband, buffers = read_blob(seg.buf)
            return True, deserialize(inband, buffers)
        if status == "shm_arena":
            seg = self.segments.open(reply["arena_name"])
            off, size = reply["offset"], reply["size"]
            region = memoryview(seg.buf)[off : off + size]
            inband, buffers = read_blob(region)
            return True, deserialize(inband, buffers)
        return False, None

    async def _get_one(self, ref: ObjectRef, deadline: float,
                       prio: int = 0) -> Any:
        oid = ref.id
        lost_hint = False
        while True:
            # 1. local memory store (own small results)
            if oid in self.memory_store:
                return self.memory_store[oid]
            # 2. a pending local task will produce it
            fut = self._ensure_result_future(oid)
            if fut is not None and not fut.done():
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    raise GetTimeoutError(f"timed out waiting for {oid.hex()}")
                try:
                    await asyncio.wait_for(asyncio.shield(fut), timeout)
                except asyncio.TimeoutError:
                    raise GetTimeoutError(f"timed out waiting for {oid.hex()}")
                continue
            # 3. known to live in the distributed store
            if self._in_store.get(oid):
                ok, value = await self._read_local_store(
                    oid, max(0.1, deadline - time.monotonic()), prio=prio,
                    owner=ref.owner_address())
                if ok:
                    return value
                # lost from the store (e.g. the holding node died):
                # reconstruct from lineage by re-executing the producer
                self._in_store.pop(oid, None)
                if await self._recover_object(oid):
                    continue
                raise ObjectLostError(f"object {oid.hex()} lost from store "
                                      f"and not reconstructable")
            # 4. remote owner fetch (small objects / long-poll for pending)
            owner = ref.owner_address()
            if owner and owner != self.address:
                value, in_store = await self._fetch_from_owner(
                    ref, deadline, lost=lost_hint)
                lost_hint = False
                if in_store:
                    ok, value = await self._read_local_store(
                        oid, max(0.1, deadline - time.monotonic()), prio=prio,
                        owner=ref.owner_address())
                    if ok:
                        return value
                    # tell the owner on the next round so it can verify and
                    # trigger lineage reconstruction
                    lost_hint = True
                    continue
                return value
            # 5. last resort: the store via directory pull
            ok, value = await self._read_local_store(
                oid, max(0.1, min(deadline - time.monotonic(), 5.0)),
                prio=prio, owner=ref.owner_address())
            if ok:
                return value
            if time.monotonic() > deadline:
                raise GetTimeoutError(f"timed out resolving {oid.hex()}")

    async def _fetch_from_owner(self, ref: ObjectRef, deadline: float,
                                lost: bool = False):
        client = self._worker_client(ref.owner_address())
        while True:
            timeout = deadline - time.monotonic()
            if timeout <= 0:
                raise GetTimeoutError(f"timed out fetching {ref.hex()} from owner")
            try:
                reply = wire.loads(await client.call("GetOwnedObject", wire.dumps(
                    {"oid": ref.binary(), "timeout": min(timeout, 10.0),
                     "lost": lost}),
                    timeout=min(timeout, 10.0) + 5.0, retries=1))
                lost = False
            except (RpcError, asyncio.TimeoutError) as e:
                raise ObjectLostError(
                    f"owner {ref.owner_address()} of {ref.hex()} unreachable: {e}")
            status = reply["status"]
            if status == "value":
                inband, buffers = read_blob(reply["blob"])
                value = deserialize(inband, buffers)
                if isinstance(value, TaskError):
                    raise value
                return value, False
            if status == "in_store":
                return None, True
            if status == "error":
                raise loads_trusted(reply["error"])
            # pending: loop

    async def _maybe_pull_device(self, value, deadline):
        """Resolve a DeviceObjectMarker by pulling from the holder worker
        (zero-copy local hit when this worker IS the holder). Reference:
        gpu_object_manager orchestrating p2p pulls between actors."""
        from ray_tpu.experimental.device_objects import DeviceObjectMarker

        if not isinstance(value, DeviceObjectMarker):
            return value
        if value.address == self.address:
            if value.oid in self.device_store:
                return self.device_store[value.oid]
            raise ObjectLostError(
                f"device object {value.oid.hex()[:12]} was freed")
        cached = self._device_fetch_cache.get(value.oid)
        if cached is not None:
            return cached
        timeout = max(1.0, min(deadline - time.monotonic(), 300.0))
        try:
            reply = wire.loads(await self._worker_client(value.address).call(
                "GetDeviceObject", wire.dumps({"oid": value.oid}),
                timeout=timeout, retries=1, connect_timeout=5.0))
        except (RpcError, asyncio.TimeoutError) as e:
            raise ObjectLostError(
                f"holder {value.address} of device object "
                f"{value.oid.hex()[:12]} unreachable: {e}")
        if reply["status"] != "ok":
            self._device_fetch_cache.pop(value.oid, None)
            raise ObjectLostError(
                f"device object {value.oid.hex()[:12]} gone from holder "
                f"{value.address} (freed or actor restarted)")
        inband, buffers = read_blob(reply["blob"])
        fetched = deserialize(inband, buffers)
        if len(self._device_fetch_cache) > 32:
            self._device_fetch_cache.pop(next(iter(self._device_fetch_cache)))
        self._device_fetch_cache[value.oid] = fetched
        return fetched

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        deadline = time.monotonic() + (timeout if timeout is not None else 86400.0)

        async def _get_all():
            # batched lazy-future setup up front: one pass instead of one
            # _ensure_result_future probe chain per ref inside _get_one
            self._ensure_result_futures({r.id for r in refs})
            out = []
            for ref in refs:
                value = await self._get_one(ref, deadline)
                if isinstance(value, TaskError):
                    raise value
                out.append(await self._maybe_pull_device(value, deadline))
            return out

        values = self._run(_get_all())
        return values[0] if single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        """Event-driven wait (reference: raylet/wait_manager.h): locally-
        owned pending refs ride their result futures; store-resident refs
        ride ONE StoreWaitAny long-poll at the raylet (which hooks the
        store's seal events) — no per-ref per-tick RPC fan-out."""

        async def _wait():
            deadline = time.monotonic() + (timeout if timeout is not None
                                           else 86400.0)
            oid_set = {r.id for r in refs}
            while True:
                # vectorized ready partition: one pair of C-level set
                # intersections against the store indexes per poll instead
                # of two dict probes per ref per tick (visible on 1000-ref
                # wait windows). _in_store values are only ever True, so
                # key membership IS store-residency.
                ready_now = self.memory_store.keys() & oid_set
                ready_now |= self._in_store.keys() & oid_set
                self._submit_stats["wait_vector_polls"] += 1
                # batched lazy-future setup: allocate every still-pending
                # ref's result future in one pass (first poll does all the
                # work; later polls find the intersection empty)
                self._ensure_result_futures(oid_set - ready_now)
                ready, fut_pending, store_pending = [], [], []
                for r in refs:
                    oid = r.id
                    if oid in ready_now:
                        ready.append(r)
                        continue
                    fut = self._result_futures.get(oid)
                    if fut is None:
                        store_pending.append(r)
                    elif fut.done():
                        ready.append(r)
                    else:
                        fut_pending.append(fut)
                if len(ready) >= num_returns or time.monotonic() >= deadline:
                    ready = ready[:num_returns]
                    # identity filter: ready elements ARE elements of refs,
                    # so id() membership avoids the O(n*m) ObjectRef __eq__
                    # scan (visible on 1000-ref wait windows)
                    ready_ids = {id(r) for r in ready}
                    return ready, [r for r in refs if id(r) not in ready_ids]
                chunk = max(0.05, min(10.0, deadline - time.monotonic()))
                waiters = []
                if fut_pending:
                    waiters.append(asyncio.ensure_future(asyncio.wait(
                        fut_pending, return_when=asyncio.FIRST_COMPLETED)))
                if store_pending:
                    waiters.append(asyncio.ensure_future(self.raylet.call(
                        "StoreWaitAny", wire.dumps({
                            "oids": [r.binary() for r in store_pending],
                            "num_needed": 1, "timeout": chunk}),
                        timeout=chunk + 10.0, retries=0)))
                if not waiters:
                    await asyncio.sleep(0.01)
                    continue
                done, pend = await asyncio.wait(
                    waiters, return_when=asyncio.FIRST_COMPLETED,
                    timeout=chunk)
                for t in pend:
                    t.cancel()
                failed = False
                for t in done:
                    # retrieve exceptions (a StoreWaitAny to a restarting
                    # raylet fails) — unretrieved task errors spam logs
                    if not t.cancelled() and t.exception() is not None:
                        failed = True
                if failed:
                    await asyncio.sleep(0.2)  # backoff, don't churn RPCs

        return self._run(_wait())

    def as_future(self, ref):
        import concurrent.futures

        out: "concurrent.futures.Future" = concurrent.futures.Future()

        def _done(task):
            try:
                value = task.result()
                if isinstance(value, TaskError):
                    out.set_exception(value)
                else:
                    out.set_result(value)
            except Exception as e:
                out.set_exception(e)

        def _schedule():
            t = asyncio.ensure_future(self.await_ref(ref))
            t.add_done_callback(_done)

        self.loop.call_soon_threadsafe(_schedule)
        return out

    async def await_ref(self, ref):
        deadline = time.monotonic() + 86400.0
        value = await self._get_one(ref, deadline)
        if isinstance(value, TaskError):
            raise value
        return await self._maybe_pull_device(value, deadline)

    def free_objects(self, refs: List[ObjectRef]):
        async def _free():
            oids = []
            freed_in_store = []
            for r in refs:
                # a marker in the memory store points at a device-held value:
                # release that too, or it would be orphaned forever
                await self._maybe_free_device_marker(self.memory_store.get(r.id))
                self.memory_store.pop(r.id, None)
                if self._in_store.pop(r.id, None):
                    freed_in_store.append(r.binary())
                self.ref_counter.release_nested(r.binary())
                oids.append(r.binary())
            for ob in oids:
                self._obj_locations.pop(ob, None)
            if freed_in_store:
                try:
                    await self._gcs_call("ObjectFree", {"oids": freed_in_store})
                except (RpcError, asyncio.TimeoutError, OSError) as e:
                    logger.debug("ObjectFree(%d oids) to GCS failed: %s",
                                 len(freed_in_store), e)
            await self.raylet.call("StoreDelete", wire.dumps({"oids": oids}))

        self._run(_free())

    # ------------------------------------------------------------------
    # ownership refcounting + lineage (reference: reference_counter.cc,
    # task_manager.cc, object_recovery_manager.cc)
    # ------------------------------------------------------------------

    def _on_owned_zero(self, oid: bytes):
        """All local refs/pins/borrowers of an owned object released.
        Batched: the oid rides a plain deque the refcount sweep drains on
        its next 0.2s tick — no per-object loop wakeup (the grace delay
        below dwarfs the added sweep latency anyway)."""
        if self._shutdown:
            return
        self._free_zero_q.append(oid)

    def _schedule_free(self, oid: bytes):
        """Queue an owned object for freeing after the grace window (loop
        thread only). One FIFO + the sweep loop replace a per-object
        call_later timer: deadlines are appended in monotonic order, so
        the sweep pops due entries from the left."""
        if not RAY_CONFIG.distributed_refcounting or oid in self._free_pending:
            return
        self._free_pending.add(oid)
        # grace delay absorbs in-flight AddBorrower registrations
        self._free_grace_q.append(
            (time.monotonic() + RAY_CONFIG.free_grace_s, oid))

    async def _free_owned(self, oid_bytes: bytes):
        self._free_pending.discard(oid_bytes)
        rc = self.ref_counter
        if not rc.freeable(oid_bytes):
            return
        oid = ObjectID(oid_bytes)
        if self._return_pending(oid):
            return  # production in flight; completion re-checks
        is_put = bool(oid.return_index() & 0x8000)
        if rc.lineage_count(oid_bytes) > 0 and is_put:
            # a retained downstream task's args need this value and a put
            # cannot be reconstructed: keep it until the lineage releases
            return
        value = self.memory_store.pop(oid, None)
        await self._maybe_free_device_marker(value)
        self._result_futures.pop(oid, None)
        self._pending_returns.pop(oid, None)
        in_store = self._in_store.pop(oid, None)
        rc.release_nested(oid_bytes)
        self._obj_locations.pop(oid_bytes, None)
        if in_store:
            try:
                await self._gcs_call("ObjectFree", {"oids": [oid_bytes]})
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("ObjectFree(%s) to GCS failed: %s",
                             oid_bytes.hex()[:8], e)
        if rc.lineage_count(oid_bytes) == 0:
            rc.drop(oid_bytes)
        self._maybe_drop_record(oid.task_id())

    async def _maybe_free_device_marker(self, value):
        from ray_tpu.experimental.device_objects import DeviceObjectMarker

        if not isinstance(value, DeviceObjectMarker):
            return
        self._device_fetch_cache.pop(value.oid, None)
        if value.address == self.address:
            self.device_store.pop(value.oid, None)
        else:
            try:
                await self._worker_client(value.address).call(
                    "FreeDeviceObject", wire.dumps({"oid": value.oid}),
                    timeout=10.0, retries=1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("FreeDeviceObject to %s failed: %s",
                             value.address, e)

    def _on_borrow_first(self, oid: bytes, owner: str):
        """First local handle to a foreign-owned object: register as a
        borrower with the owner (debounced to skip transient handles)."""
        if self._shutdown or not owner:
            return

        def _later():
            self.loop.call_later(
                RAY_CONFIG.borrow_debounce_s,
                lambda: spawn(self._register_borrow(oid, owner),
                              what="borrow registration"))

        try:
            self.loop.call_soon_threadsafe(_later)
        except RuntimeError:  # raylint: disable=EXC001 loop already closed during shutdown; borrow is moot
            pass

    async def _register_borrow(self, oid: bytes, owner: str):
        """Tell the owner we hold a borrow. Retries until acked (never a
        silent drop — a lost registration means the owner frees an object a
        live borrower still needs); removal is owner-initiated via the
        WaitBorrowsDone watch, so there is no add/remove ordering race."""
        rc = self.ref_counter
        delay = 0.1
        for _ in range(8):
            if rc.held_count(oid) <= 0 or self._shutdown:
                return
            try:
                await self._worker_client(owner).call("AddBorrower", wire.dumps(
                    {"oid": oid, "address": self.address}),
                    timeout=10.0, retries=1)
                return
            except (RpcError, asyncio.TimeoutError, OSError):
                await asyncio.sleep(delay)
                delay = min(delay * 2, 5.0)
        logger.warning("borrow registration for %s with owner %s kept "
                       "failing; object may be freed under us",
                       ObjectID(oid).hex()[:12], owner)

    # -- owner side: borrow lifetime watches (reference: WaitForRefRemoved,
    # reference_counter.cc — the owner subscribes to each borrower and drops
    # the borrow when the borrower reports release OR becomes unreachable) --

    def _watch_borrower(self, oid: bytes, addr: str):
        if not addr or addr == self.address or self._shutdown:
            return
        watch = self._borrow_watch_sets.setdefault(addr, {})
        watch[oid] = watch.get(oid, 0) + 1  # new registration generation
        if addr not in self._borrow_watch_active:
            self._borrow_watch_active.add(addr)
            spawn(self._borrow_watch_loop(addr), what="borrow watch loop")

    async def _borrow_watch_loop(self, addr: str):
        """One long-poll loop per borrower address covering all its borrowed
        oids; a dead borrower (sustained unreachability, ~1 min of strikes)
        releases everything. Borrowers also periodically re-assert held
        borrows (_reassert_borrows), so a wrongly-reclaimed live borrower
        re-registers unless the object was already freed in the gap."""
        failing_since = None
        delay = 1.0
        try:
            while not self._shutdown:
                snap = dict(self._borrow_watch_sets.get(addr, {}))
                if not snap:
                    return
                try:
                    reply = wire.loads(await self._worker_client(addr).call(
                        "WaitBorrowsDone",
                        wire.dumps({"oids": list(snap)}),
                        timeout=40.0, retries=0, connect_timeout=5.0))
                    failing_since = None
                    delay = 1.0
                    done = reply["done"]
                except RpcApplicationError:
                    # the borrower REPLIED (it is alive) — a handler error
                    # is not a death signal; keep watching
                    await asyncio.sleep(1.0)
                    continue
                except (RpcError, asyncio.TimeoutError, OSError):
                    now = time.monotonic()
                    if failing_since is None:
                        failing_since = now
                    if now - failing_since < RAY_CONFIG.borrower_death_timeout_s:
                        await asyncio.sleep(delay)
                        delay = min(delay * 2, 10.0)
                        continue
                    done = list(snap)  # borrower dead: reclaim its borrows
                watch = self._borrow_watch_sets.get(addr, {})
                for oid in done:
                    if watch.get(oid) != snap.get(oid):
                        continue  # re-registered while the probe was out
                    watch.pop(oid, None)
                    self.ref_counter.remove_borrower(oid, addr)
        finally:
            self._borrow_watch_active.discard(addr)
            rest = self._borrow_watch_sets.get(addr)
            if not rest:
                self._borrow_watch_sets.pop(addr, None)
            elif not self._shutdown:
                # respawn covers exceptions / adds that raced the exit;
                # re-assert the existing generation rather than minting one
                self._borrow_watch_active.add(addr)
                spawn(self._borrow_watch_loop(addr), what="borrow watch loop")

    def _register_lineage(self, task_id: TaskID, record: dict):
        """Retain the task record for reconstruction while its outputs are
        referenced; cap total retained bytes (reference: task_manager.h:183
        max_lineage_bytes)."""
        self._tasks[task_id] = record
        for oid, _owner in record.get("arg_refs", ()):
            self.ref_counter.lineage_add(oid)
        self._lineage_bytes += record.get("bytes", 0)
        cap = RAY_CONFIG.max_lineage_bytes
        if self._lineage_bytes <= cap:
            return
        for tid, rec in list(self._tasks.items()):
            if self._lineage_bytes <= cap:
                break
            if rec is record or rec.get("_recover_event") is not None:
                continue
            fut_pending = any(self._return_pending(rid)
                              for rid in rec.get("return_ids", ()))
            if fut_pending:
                continue
            self._drop_record(tid, rec)  # outputs become non-reconstructable

    def _maybe_drop_record(self, task_id: TaskID):
        rec = self._tasks.get(task_id)
        if rec is None or rec.get("_recover_event") is not None:
            return
        rc = self.ref_counter
        for rid in rec.get("return_ids", ()):
            b = rid.binary()
            if not rc.freeable(b) or rc.lineage_count(b) > 0:
                return
            if self._return_pending(rid):
                return
        self._drop_record(task_id, rec)

    def _drop_record(self, task_id: TaskID, rec: dict):
        self._tasks.pop(task_id, None)
        self.stream_release(task_id)
        self._lineage_bytes -= rec.get("bytes", 0)
        rc = self.ref_counter
        for rid in rec.get("return_ids", ()):
            if rc.lineage_count(rid.binary()) == 0 and rc.freeable(rid.binary()):
                rc.drop(rid.binary())
        for oid, owner in rec.get("arg_refs", ()):
            rc.lineage_remove(oid)
            if not owner or owner == self.address:
                # the arg may now be fully releasable (cascades up the DAG)
                if rc.freeable(oid) and rc.lineage_count(oid) == 0:
                    self._schedule_free(oid)
                self._maybe_drop_record(ObjectID(oid).task_id())

    def _release_task_pins(self, record: dict):
        if record.pop("_pinned", None):
            for oid, _owner in record.get("arg_refs", ()):
                self.ref_counter.unpin(oid)

    def _process_reply_refs(self, reply: dict, executor_addr: str):
        """Handle borrow/nested-ref reports carried on a task reply (the
        reliable registration leg; removal is owner-initiated via watches)."""
        for oid, owner in reply.get("borrows", ()):
            if not owner or owner == self.address:
                self.ref_counter.add_borrower(oid, executor_addr)
                self._watch_borrower(oid, executor_addr)
            else:
                spawn(self._forward_borrow(owner, oid, executor_addr),
                      what="borrow forward")
        nested = reply.get("nested") or {}
        for ret_oid, inner in nested.items():
            self.ref_counter.pin_nested(ret_oid, list(inner))
            for oid, owner in inner:
                if owner and owner != self.address:
                    spawn(self._forward_borrow(owner, oid, self.address),
                          what="borrow forward")

    async def _forward_borrow(self, owner: str, oid: bytes, borrower: str):
        try:
            await self._worker_client(owner).call("AddBorrower", wire.dumps(
                {"oid": oid, "address": borrower}), timeout=10.0, retries=1)
        except (RpcError, asyncio.TimeoutError, OSError) as e:
            logger.debug("AddBorrower(%s) forward to owner %s failed: %s",
                         oid.hex()[:8], owner, e)

    async def _recover_object(self, oid: ObjectID) -> bool:
        """Lineage reconstruction: re-execute the producing task (reference:
        object_recovery_manager.h:41). Returns True if a re-execution was
        run (caller re-checks the object)."""
        rec = self._tasks.get(oid.task_id())
        if rec is None:
            return False
        ev = rec.get("_recover_event")
        if ev is not None:
            await ev.wait()
            return True
        if rec.get("_recoveries", 0) >= RAY_CONFIG.max_object_reconstructions:
            return False
        rec["_recoveries"] = rec.get("_recoveries", 0) + 1
        rec["_recover_event"] = ev = asyncio.Event()
        logger.warning("object %s lost; reconstructing via lineage re-execution "
                       "of %s (recovery %d)", oid.hex()[:12], rec["name"],
                       rec["_recoveries"])
        try:
            for rid in rec["return_ids"]:
                self._in_store.pop(rid, None)
                self.memory_store.pop(rid, None)
                old = self._result_futures.get(rid)
                if old is not None and old.done():
                    self._result_futures.pop(rid, None)
                # re-mark pendency; a waiter re-allocates the future lazily
                self._pending_returns[rid] = True
            rec["attempts"] = 0  # fresh retry budget for the recovery run
            for ob, ow in rec.get("arg_refs", ()):
                self.ref_counter.pin(ob, ow)
            rec["_pinned"] = True
            await self._drive_task(rec)
        finally:
            rec.pop("_recover_event", None)
            ev.set()
        return True

    # ------------------------------------------------------------------
    # task submission (owner side)
    # ------------------------------------------------------------------

    def submit_task(self, remote_fn, args, kwargs, opts: TaskOptions):
        """Non-blocking submission: everything cheap happens on the caller
        thread; the drive coroutine is kicked off fire-and-forget so batched
        ``.remote()`` loops pipeline instead of paying a cross-thread round
        trip per call (reference: the owner-side submit path is the tasks/s
        hot loop, normal_task_submitter.cc).

        Warm path (template cached for this RemoteFunction, tracing off):
        the spec reuses the resolved function key + prepared options and
        carries a preserialized template blob, so the per-submit work is
        arg serialization + bookkeeping inserts — the spec is never
        re-framed through ``wire.dumps``, no future/coroutine is allocated,
        and the batch pusher ships ``(task_id, args, attempt)`` against the
        template."""
        from ray_tpu.util import tracing

        stats = self._submit_stats
        stats["count"] += 1
        task_id = TaskID.of(self.job_id)
        streaming = opts.num_returns == "streaming"
        nret = 0 if streaming else opts.num_returns
        refs = [ObjectRef(ObjectID.for_task_return(task_id, i), self.address)
                for i in range(nret)]
        t0 = time.perf_counter()
        args_blob, arg_refs = self._pack_args(args, kwargs)
        stats["serialize_s"] += time.perf_counter() - t0
        cached = self._spec_template_cache.get(id(remote_fn))
        fast = (cached is not None and cached[0] is remote_fn
                and not tracing.enabled())
        if fast:
            _rf, fn_key, popts, pool, tmpl_blob = cached
            opts = popts
        else:
            fn_key, pool, tmpl_blob = "", None, None
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            function_key=fn_key,  # empty -> filled by _drive_task_prepared
            args_blob=args_blob,
            num_returns=-1 if streaming else nret,
            options=opts,
            owner_address=self.address,
        )
        max_retries = opts.max_retries if opts.max_retries >= 0 else RAY_CONFIG.task_max_retries
        record = {"spec": spec, "attempts": 0, "max_retries": max_retries,
                  "return_ids": [ref.id for ref in refs],
                  "arg_refs": arg_refs, "bytes": len(args_blob) + 512,
                  "name": remote_fn.function_name,
                  "_submit_ts": time.time()}
        if tmpl_blob is not None:
            record["_tmpl"] = tmpl_blob
        if not fast:
            self._stamp_trace(spec, record["name"])
        if task_events.enabled():
            record["_job_hex"] = jh = self.job_id.hex()
            t1 = time.perf_counter()
            task_events.record_submitted(
                task_id.hex(), record["_submit_ts"], record["name"], jh,
                len(args_blob), _task_span_id(spec), self._submitter_span())
            stats["events_s"] += time.perf_counter() - t1
        for oid, owner in arg_refs:
            self.ref_counter.pin(oid, owner)
        record["_pinned"] = True
        for ref in refs:
            # marked off-loop so a get() racing the kickoff sees pendency;
            # the future itself is allocated lazily on first get/await
            # raylint: disable=RCE001 dict stores are single-bytecode and the loop-side recovery write is the same idempotent True — the off-loop marking is the point (see comment above)
            self._pending_returns[ref.id] = True
        if streaming:
            # per-stream state the executor's StreamTaskReturn RPCs fill
            self._streams[task_id.binary()] = {
                "produced": 0, "total": None, "error": None,
                "event": asyncio.Event()}
        t2 = time.perf_counter()
        if fast:
            stats["fast_path"] += 1

            def _kickoff():
                self._register_lineage(task_id, record)
                if record.get("_cancelled") \
                        or self._has_pending_local_deps(record):
                    spawn(self._drive_task(record, wait=False),
                          what="task drive")
                    return
                if task_events.enabled():
                    task_events.record(
                        task_id.hex(), task_events.LEASE_REQUESTED,
                        attempt=spec.attempt,
                        job_id=record.get("_job_hex", ""))
                pool.submit(record)
        else:
            def _kickoff():
                self._register_lineage(task_id, record)
                spawn(self._drive_task_prepared(remote_fn, record),
                      what="task drive")

        self._queue_kickoff(_kickoff)
        stats["kickoff_s"] += time.perf_counter() - t2
        if streaming:
            from ray_tpu.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(self, task_id, self.address)
        return refs[0] if nret == 1 else refs

    def _has_pending_local_deps(self, record: dict) -> bool:
        """Sync form of _resolve_dependencies' wait condition: does any
        locally-owned ref arg still have its producer in flight?"""
        for oid_b, owner in record.get("arg_refs", ()):
            if (not owner or owner == self.address) \
                    and self._return_pending(ObjectID(oid_b)):
                return True
        return False

    def submit_stats(self) -> dict:
        """Per-submit cost breakdown (µs, amortized over all submits so
        far): the serialize/events/kickoff legs are caller-thread wall
        time; push_rtt is the PushTaskBatch round trip INCLUDING remote
        execution, amortized per task (round trips overlap across pushers,
        so it is an upper bound on the owner-side push cost)."""
        s = dict(self._submit_stats)
        n = max(1, s["count"])
        s["per_submit_us"] = {
            "serialize": round(s["serialize_s"] / n * 1e6, 2),
            "events": round(s["events_s"] / n * 1e6, 2),
            "kickoff": round(s["kickoff_s"] / n * 1e6, 2),
            "push_rtt": round(s["push_s"] / max(1, s["push_tasks"]) * 1e6, 2),
        }
        return s

    def _submitter_span(self) -> str:
        """The submitter's active span id (the enclosing task's execution
        span, or a user ``profile()`` block) — rides the SUBMITTED task
        event so the GCS timeline can join parent→child task records into
        flow arrows without reading the span table. Empty when tracing is
        off (arrows are a tracing feature; slices still render)."""
        from ray_tpu.util import tracing

        ctx = tracing.current_context()
        return ctx[1] if ctx is not None else ""

    def _stamp_trace(self, spec: TaskSpec, name: str):
        """Propagate the caller's trace context into the spec (reference:
        tracing_helper.py injecting the OTel context into the TaskSpec).
        Records a zero-width ``submit`` span as the flow-arrow anchor: the
        driver (or enclosing task) side of the driver→worker edge. No-op
        unless tracing is enabled."""
        from ray_tpu.util import tracing

        if not tracing.enabled():
            return
        ctx = tracing.current_context()
        trace_id = ctx[0] if ctx is not None else tracing.new_trace_id()
        span_id = tracing.new_span_id()
        now = time.time()
        tracing.record_span(
            f"submit:{name}", now, now, category="submit",
            trace_id=trace_id, span_id=span_id,
            parent_id=ctx[1] if ctx is not None else None,
            task_id=spec.task_id.hex())
        spec.trace_id = trace_id
        spec.parent_span_id = span_id

    async def _drive_task_prepared(self, remote_fn, record: dict):
        """Resolve the (cached) function key + runtime env, then drive."""
        spec: TaskSpec = record["spec"]
        try:
            if spec.options.runtime_env or self.job_runtime_env:
                spec.options.runtime_env = await self._prepare_runtime_env(
                    spec.options.runtime_env)
            spec.function_key = await self._push_function(remote_fn.function)
        except Exception as e:
            self._complete_error(record, TaskError(
                f"submission failed for {record['name']}: {e}",
                traceback.format_exc()))
            return
        self._cache_spec_template(remote_fn, spec)
        # fire-and-forget: completion flows through the result futures; only
        # recovery re-execution needs to await the record (saves a coroutine
        # suspension+wake per task on the submit hot path)
        await self._drive_task(record, wait=False)

    def _cache_spec_template(self, remote_fn, spec: TaskSpec):
        """Frame the invariant part of this (function, options) pair's spec
        ONCE: later submits reuse the blob (see submit_task's warm path)
        and the pusher ships only (task_id, args_blob, attempt) against it.
        The prepared options (runtime env uploaded, function key resolved)
        and the lease pool ride along so the warm path does no awaits."""
        import copy as _copy

        cached = self._spec_template_cache.get(id(remote_fn))
        if cached is not None and cached[0] is remote_fn:
            return
        if len(self._spec_template_cache) >= 1024:
            # bound the cache: `f.options(...).remote()` mints a NEW
            # RemoteFunction per call, so without eviction a submit loop
            # over one-shot options objects grows this (and the strong
            # refs in slot 0) without limit. A full clear is fine — live
            # functions re-frame once each (counted in spec_frames).
            self._spec_template_cache.clear()
        tmpl = _copy.copy(spec)
        tmpl.task_id = TaskID.nil()
        tmpl.args_blob = b""
        tmpl.attempt = 0
        tmpl.trace_id = ""
        tmpl.parent_span_id = ""
        blob = wire.dumps(tmpl)
        self._submit_stats["spec_frames"] += 1
        pool = self._lease_pool_for(spec.options,
                                    spec.options.required_resources())
        self._spec_template_cache[id(remote_fn)] = (
            remote_fn, spec.function_key, spec.options, pool, blob)

    # pooled-scratch ceiling: args bigger than this pack into a one-shot
    # buffer instead of pinning multi-MB scratch per submitting thread
    _PACK_SCRATCH_MAX = 4 << 20

    def _pack_args(self, args, kwargs):
        # inline small owned values so the executor need not call back
        def _inline(v):
            if isinstance(v, ObjectRef) and v.id in self.memory_store:
                value = self.memory_store[v.id]
                if not isinstance(value, TaskError):
                    return value
            return v

        from ray_tpu.object_ref import collect_serialized_refs

        args = tuple(_inline(a) for a in args)
        kwargs = {k: _inline(v) for k, v in kwargs.items()}
        with collect_serialized_refs() as arg_refs:
            inband, buffers = serialize((args, kwargs))
        # pooled serialization scratch (per submitting thread — submits
        # come from user threads as well as the loop): pack into a reused
        # bytearray and snapshot once, instead of pack_blob's
        # alloc-bytearray + copy-to-bytes per call. At ~31 µs/submit the
        # per-driver ceiling is arg-serialization-bound (STRESS_r07);
        # killing the large-allocation churn is the cheap half of that.
        stats = self._submit_stats
        total, offsets = plan_layout(inband, buffers)
        scratch = getattr(self._tls, "pack_scratch", None)
        if scratch is not None and len(scratch) >= total:
            stats["pack_pool_hits"] += 1
        else:
            stats["pack_pool_misses"] += 1
            size = min(max(total, 64 << 10), self._PACK_SCRATCH_MAX)
            if total <= self._PACK_SCRATCH_MAX:
                scratch = self._tls.pack_scratch = bytearray(size)
            else:  # oversized: one-shot buffer, never pooled
                scratch = bytearray(total)
        write_blob(scratch, inband, buffers, offsets)
        # pack_blob's fresh bytearray had zeroed alignment gaps; the
        # reused scratch keeps a PRIOR submit's bytes there — zero the
        # gaps (each <64 B) so blobs stay deterministic and never leak
        # another task's argument fragments to the executor
        mv = memoryview(scratch)
        prev_end = 16 + 16 * len(buffers) + len(inband)
        for b, off in zip(buffers, offsets):
            if off > prev_end:
                mv[prev_end:off] = bytes(off - prev_end)
            prev_end = off + b.nbytes
        if total > prev_end:
            mv[prev_end:total] = bytes(total - prev_end)
        blob = bytes(mv[:total])
        return blob, arg_refs

    async def _resolve_dependencies(self, record: dict):
        """Wait for locally-owned ref args to finish producing before the
        task becomes push-eligible (reference: dependency_resolver.cc, used
        by normal_task_submitter.cc:32). This keeps batched pushes
        dependency-safe: a task can never ride the same PushTaskBatch as its
        own producer, whose result would otherwise be trapped in the batch's
        unreturned reply."""
        for oid_b, owner in record.get("arg_refs", ()):
            if owner and owner != self.address:
                continue  # foreign-owned: the executor resolves via that owner
            fut = self._ensure_result_future(ObjectID(oid_b))
            if fut is not None and not fut.done():
                await asyncio.shield(fut)

    async def _drive_task(self, record: dict, wait: bool = True):
        """Queue onto the scheduling-key pool (lease reuse + batched pushes;
        reference: normal_task_submitter.cc + task_manager.cc). Retries on
        worker failure happen inside the pool; ``wait`` is only needed by
        recovery re-execution (normal completion flows through futures)."""
        spec: TaskSpec = record["spec"]
        opts: TaskOptions = spec.options
        await self._resolve_dependencies(record)
        if record.get("_cancelled"):
            from ray_tpu.exceptions import TaskCancelledError

            self._complete_error(record, TaskCancelledError())
            return
        if task_events.enabled():
            task_events.record(spec.task_id.hex(), task_events.LEASE_REQUESTED,
                               attempt=spec.attempt,
                               job_id=record.get("_job_hex", ""))
        pool = self._lease_pool_for(opts, opts.required_resources())
        if wait:
            # only recovery re-execution blocks on the record; the normal
            # path skips the per-task Event allocation entirely
            record["_done"] = asyncio.Event()
        pool.submit(record)
        if wait:
            await record["_done"].wait()

    def _observe_complete(self, record, err: Optional[TaskError],
                          ret_bytes: int = 0):
        """Terminal lifecycle event + end-to-end latency histogram (the
        always-on half of observability: costs one histogram observe and,
        when task events are on, a buffered append)."""
        submit_ts = record.get("_submit_ts")
        if submit_ts is not None and record.get("name"):
            try:
                _obs()["e2e"].observe(time.time() - submit_ts,
                                      tags={"function": record["name"]})
            except Exception:  # raylint: disable=EXC001 metrics must never fail a task completion
                pass
        if task_events.enabled():
            spec = record["spec"]
            task_events.record(
                spec.task_id.hex(),
                task_events.FAILED if err is not None else task_events.FINISHED,
                attempt=max(record.get("attempts", 0),
                            record.get("epoch", 0) or 0),
                error=str(err) if err is not None else "",
                job_id=record.get("_job_hex", ""),
                ret_bytes=ret_bytes)

    @staticmethod
    def _result_nbytes(results) -> int:
        """Serialized return-payload bytes of a completed task: inline
        results carry their blob, store-resident ones ride the executor's
        size annotation (the payload slot of a ``("store", nbytes)``
        result tuple)."""
        total = 0
        for kind, payload in results:
            if kind == "inline":
                total += len(payload)
            elif isinstance(payload, int):
                total += payload
        return total

    def _complete_ok(self, record, results, stream_count=None):
        record["_completed"] = True
        self._observe_complete(record, None,
                               ret_bytes=self._result_nbytes(results))
        if record["spec"].num_returns == -1:
            st = self._streams.get(record["spec"].task_id.binary())
            if st is not None:
                st["total"] = stream_count if stream_count is not None \
                    else st["produced"]
                ev, st["event"] = st["event"], asyncio.Event()
                ev.set()
        for oid, (kind, payload) in zip(record["return_ids"], results):
            if kind == "inline":
                inband, buffers = read_blob(payload)
                self.memory_store[oid] = deserialize(inband, buffers)
            else:  # stored in the distributed object store
                self._in_store[oid] = True
            self._pending_returns.pop(oid, None)
            fut = self._result_futures.get(oid)
            if fut is not None and not fut.done():
                fut.set_result(True)
        self._release_task_pins(record)
        done = record.get("_done")
        if done is not None:
            done.set()
        for oid in record["return_ids"]:
            if self.ref_counter.freeable(oid.binary()):
                self._schedule_free(oid.binary())

    def _complete_error(self, record, err: TaskError):
        record["_completed"] = True
        self._observe_complete(record, err)
        streaming = record["spec"].num_returns == -1
        if streaming:
            st = self._streams.get(record["spec"].task_id.binary())
            if st is not None:
                st["error"] = err
                ev, st["event"] = st["event"], asyncio.Event()
                ev.set()
        for oid in record["return_ids"]:
            if streaming and (oid in self.memory_store
                              or self._in_store.get(oid)):
                self._pending_returns.pop(oid, None)
                continue  # already-yielded items stay readable
            self.memory_store[oid] = err
            self._pending_returns.pop(oid, None)
            fut = self._result_futures.get(oid)
            if fut is not None and not fut.done():
                fut.set_result(True)
        self._release_task_pins(record)
        done = record.get("_done")
        if done is not None:
            done.set()
        # re-schedule frees that _free_owned deferred while production was
        # in flight (same re-check _complete_ok does): without it an error
        # object whose refs were all dropped mid-flight stays in
        # memory_store forever
        for oid in record["return_ids"]:
            if self.ref_counter.freeable(oid.binary()):
                self._schedule_free(oid.binary())

    # -- leases --

    def _lease_pool_for(self, opts: TaskOptions, resources) -> _LeasePool:
        from ray_tpu._private.runtime_env import env_hash

        key = (_freeze(resources), _freeze(opts.label_selector),
               opts.placement_group.id.binary() if opts.placement_group else None,
               opts.placement_group_bundle_index,
               env_hash(opts.runtime_env))
        pool = self._lease_cache.get(key)
        if pool is None:
            pool = _LeasePool(self, key, opts, resources)
            self._lease_cache[key] = pool
        return pool

    async def _arg_locality(self, record: dict):
        """Byte-weighted argument locations for a task (reference:
        task_submission/lease_policy.cc LocalityAwareLeasePolicy): returns
        ({node_hex: bytes}, best_address) using the GCS object directory
        (sizes ride the location announcements), briefly cached per oid.
        None when args are inline/small — locality cannot beat the local
        start then."""
        arg_refs = record.get("arg_refs") or ()
        if not arg_refs:
            return None, None
        if not hasattr(self, "_loc_cache"):
            self._loc_cache = {}
        by_node: Dict[str, int] = {}
        addr_of: Dict[str, str] = {}
        now = time.monotonic()
        for oid, _owner in arg_refs:
            key = oid.binary() if hasattr(oid, "binary") else oid
            own = self._obj_locations.get(key)
            if own is not None:
                # owner-resident: this worker owns the object — its own
                # table answers without any directory RPC
                size = own.get("size", 0) or 0
                for n, a in own["nodes"].items():
                    by_node[n] = by_node.get(n, 0) + size
                    addr_of[n] = a
                continue
            hit = self._loc_cache.get(key)
            if hit is not None and now - hit[0] < 5.0:
                reply = hit[1]
            else:
                try:
                    reply = await self._gcs_call(
                        "ObjectLocGet", {"oid": key}, timeout=5.0)
                except Exception as e:
                    logger.debug("ObjectLocGet(%s) failed; skipping this "
                                 "pull round: %s", key.hex()[:8], e)
                    continue
                if len(self._loc_cache) > 4096:
                    self._loc_cache.clear()
                self._loc_cache[key] = (now, reply)
            size = reply.get("size") or 0  # None: deleted-before-announce
            for loc in reply.get("locations", ()):
                by_node[loc["node_id"]] = by_node.get(loc["node_id"], 0) + size
                addr_of[loc["node_id"]] = loc["address"]
        if not by_node:
            return None, None
        best = max(by_node, key=by_node.get)
        if by_node[best] < RAY_CONFIG.locality_min_arg_bytes:
            return by_node, None
        return by_node, addr_of.get(best)

    async def _pick_node(self, opts: TaskOptions, resources) -> Optional[dict]:
        strat = opts.scheduling_strategy
        if opts.placement_group is not None:
            reply = await self._gcs_call("GetPlacementGroup",
                                         {"pg_id": opts.placement_group.id.binary()})
            info = reply["info"]
            if info is None or info["state"] != "CREATED":
                # wait for the pg
                await self._gcs_call("WaitPlacementGroupReady", {
                    "pg_id": opts.placement_group.id.binary(), "timeout": 300.0},
                    timeout=310.0)
                reply = await self._gcs_call("GetPlacementGroup",
                                             {"pg_id": opts.placement_group.id.binary()})
                info = reply["info"]
                if info is None:
                    return None
            idx = max(opts.placement_group_bundle_index, 0)
            node_hex = info["bundle_nodes"][idx]
            nodes = (await self._gcs_call("GetAllNodes", {}))["nodes"]
            for n in nodes:
                if n["node_id"] == node_hex:
                    return {"node_id": node_hex, "address": n["address"]}
            return None
        selector = dict(opts.label_selector)
        req: Dict[str, Any] = {"resources": resources, "selector": selector}
        if strat is not None:
            if hasattr(strat, "node_id"):
                nodes = (await self._gcs_call("GetAllNodes", {}))["nodes"]
                for n in nodes:
                    if n["node_id"] == strat.node_id and n.get("alive", True):
                        return {"node_id": strat.node_id, "address": n["address"]}
                if not getattr(strat, "soft", False):
                    return None
                # soft affinity: fall through to the normal pick
            if hasattr(strat, "hard"):
                selector.update(strat.hard)
                req["selector"] = selector
            if type(strat).__name__ == "SpreadSchedulingStrategy" or strat == "SPREAD":
                self._spread_hint += 1
                req["strategy"] = "SPREAD"
                req["spread_hint"] = self._spread_hint
        deadline = time.monotonic() + RAY_CONFIG.infeasible_task_timeout_s
        warned = False
        # one demand unit per concurrent pick, stable across its retries, so
        # the GCS autoscaler view counts waiters rather than poll attempts
        req.setdefault("waiter_id", uuid.uuid4().hex)
        while True:
            reply = await self._gcs_call("PickNode", req)
            if reply["node"] is not None:
                return reply["node"]
            if not warned:
                logger.warning("no feasible node yet for resources=%s selector=%s; waiting",
                               resources, selector)
                warned = True
            if time.monotonic() > deadline:
                return None
            await asyncio.sleep(0.5)

    async def _drop_lease(self, lease: dict):
        try:
            await self._raylet_client(lease["raylet_address"]).call(
                "ReturnWorkerLease", wire.dumps({"lease_id": lease["lease_id"]}),
                timeout=5.0, retries=1)
        except (RpcError, asyncio.TimeoutError, OSError) as e:
            logger.debug("ReturnWorkerLease to %s failed: %s",
                         lease["raylet_address"], e)

    # ------------------------------------------------------------------
    # actors (owner side)
    # ------------------------------------------------------------------

    def create_actor(self, actor_cls, args, kwargs, opts: ActorOptions):
        from ray_tpu.actor import ActorHandle

        actor_id = ActorID.of(self.job_id)
        info = self._run(self._create_actor_async(actor_cls, args, kwargs, opts, actor_id))
        aid = ActorID.from_hex(info["actor_id"])
        view = self._actors.setdefault(aid, _ActorView(aid))
        view.state = info["state"]
        view.address = info["address"]
        view.max_task_retries = opts.max_task_retries
        return ActorHandle(aid, actor_cls.method_names(), actor_cls.class_name,
                           opts.max_task_retries)

    async def _create_actor_async(self, actor_cls, args, kwargs, opts, actor_id):
        opts.runtime_env = await self._prepare_runtime_env(opts.runtime_env)
        function_key = await self._push_function(actor_cls.cls)
        task_id = TaskID.of(self.job_id)
        args_blob, arg_refs = self._pack_args(args, kwargs)
        # creation args may carry refs; pin them for the actor's lifetime
        # (restarts re-resolve them from this owner)
        for oid, owner in arg_refs:
            self.ref_counter.pin(oid, owner)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            function_key=function_key,
            args_blob=args_blob,
            num_returns=0,
            options=opts,
            owner_address=self.address,
            actor_id=actor_id,
            is_actor_creation=True,
            actor_options=opts,
        )
        reply = await self._gcs_call("CreateActor", {
            "spec": spec, "class_name": actor_cls.class_name})
        if reply["status"] == "name_taken":
            raise ValueError(f"actor name {opts.name!r} already taken")
        return reply["info"]

    def _actor_view(self, actor_id: ActorID) -> _ActorView:
        view = self._actors.get(actor_id)
        if view is None:
            view = _ActorView(actor_id)
            self._actors[actor_id] = view
            # seed state from GCS
            async def _seed():
                reply = await self._gcs_call("GetActorInfo", {"actor_id": actor_id.binary()})
                info = reply["info"]
                if info is not None and view.state == "PENDING_CREATION":
                    view.state = info["state"]
                    view.address = info["address"]
            asyncio.run_coroutine_threadsafe(_seed(), self.loop)
        return view

    def submit_actor_task(self, handle, method_name, args, kwargs, num_returns=1,
                          tensor_transport=""):
        """Non-blocking (see submit_task): actor calls pipeline without a
        per-call cross-thread round trip."""
        task_id = TaskID.of(self.job_id)
        streaming = num_returns == "streaming"
        nret = 0 if streaming else num_returns
        refs = [ObjectRef(ObjectID.for_task_return(task_id, i), self.address)
                for i in range(nret)]
        args_blob, arg_refs = self._pack_args(args, kwargs)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            function_key="",
            args_blob=args_blob,
            num_returns=-1 if streaming else nret,
            options=TaskOptions(num_returns=-1 if streaming else nret),
            owner_address=self.address,
            actor_id=handle.actor_id,
            method_name=method_name,
            tensor_transport=tensor_transport,
        )
        if streaming:
            # same owner-side stream state as task generators; the
            # executor's StreamTaskReturn RPCs fill it (reference: the
            # dynamic-returns protocol works identically for actor tasks)
            self._streams[task_id.binary()] = {
                "produced": 0, "total": None, "error": None,
                "event": asyncio.Event()}
        record = {"spec": spec, "attempts": 0,
                  "max_retries": handle._max_task_retries,
                  "return_ids": [ref.id for ref in refs],
                  "arg_refs": arg_refs,
                  "name": f"{handle._class_name}.{method_name}",
                  "_submit_ts": time.time()}
        self._stamp_trace(spec, record["name"])
        if task_events.enabled():
            record["_job_hex"] = jh = self.job_id.hex()
            task_events.record_submitted(
                task_id.hex(), record["_submit_ts"], record["name"], jh,
                len(args_blob), _task_span_id(spec), self._submitter_span())
        for oid, owner in arg_refs:
            self.ref_counter.pin(oid, owner)
        record["_pinned"] = True
        for ref in refs:
            # lazy result futures, same as submit_task
            self._pending_returns[ref.id] = True

        def _kickoff():
            view = self._actor_view(handle.actor_id)
            self._actor_inflight[task_id] = record
            spawn(self._drive_actor_task(view, record), what="actor-task drive")

        self._queue_kickoff(_kickoff)
        if streaming:
            from ray_tpu.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(self, task_id, self.address)
        return refs[0] if nret == 1 else refs

    async def _drive_actor_task(self, view: _ActorView, record: dict):
        try:
            await self._drive_actor_task_inner(view, record)
        finally:
            self._actor_inflight.pop(record["spec"].task_id, None)

    async def _drive_actor_task_inner(self, view: _ActorView, record: dict):
        from ray_tpu.exceptions import TaskCancelledError

        spec: TaskSpec = record["spec"]
        deadline = time.monotonic() + 3600.0
        while True:
            if record.get("_cancelled") and not record.get("_pushed_to"):
                # cancelled while waiting for the actor: never push
                self._complete_error(record, TaskCancelledError())
                return
            if view.state == "DEAD":
                self._complete_error(record, TaskError(
                    f"ActorDiedError: actor {view.actor_id.hex()[:12]} is dead "
                    f"({view.death_cause})", "", ActorDiedError(view.death_cause)))
                return
            if view.state != "ALIVE" or not view.address:
                # wait for restart / creation (reference: actor_task_submitter
                # queues calls while the actor is restarting)
                reply = await self._gcs_call("WaitActorReady", {
                    "actor_id": view.actor_id.binary(), "timeout": 60.0}, timeout=70.0)
                info = reply["info"]
                if info is None:
                    self._complete_error(record, TaskError(
                        "ActorDiedError: actor record missing", ""))
                    return
                if info["address"] != view.address:
                    # new incarnation: per-caller ordering restarts at 1
                    view.seqno = 0
                view.state, view.address = info["state"], info["address"]
                if time.monotonic() > deadline:
                    self._complete_error(record, TaskError(
                        "ActorUnavailableError: timed out waiting for actor", ""))
                    return
                continue
            try:
                # seqno is assigned at push time so ordering is per-incarnation
                # (a restarted actor's queue starts over at 1)
                view.seqno += 1
                spec.seqno = view.seqno
                record["epoch"] = record.get("epoch", -1) + 1
                spec.attempt = record["epoch"]
                record["_pushed_to"] = view.address
                if task_events.enabled():
                    task_events.record(
                        spec.task_id.hex(), task_events.SCHEDULED,
                        attempt=record["epoch"], worker=view.address,
                        job_id=record.get("_job_hex", ""))
                # short connect timeout + one blind reconnect: the address came
                # from an ALIVE view, so an unreachable peer means the view is
                # stale — fail fast into the GCS recheck below (the real retry
                # loop) rather than camping on connect; the single presend
                # round covers the connect-then-instant-RST race on live peers
                reply = wire.loads(await self._worker_client(view.address).call(
                    "PushTask", wire.dumps({"spec": spec}), timeout=86400.0,
                    retries=0, connect_timeout=2.0, presend_retries=1))
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                view.state = "UNKNOWN"
                record.pop("_pushed_to", None)  # not running anywhere now
                if record.get("_cancelled"):
                    # cancelled + push failed: never re-push to the next
                    # incarnation (the normal-task path's :215 recheck)
                    self._complete_error(record, TaskCancelledError())
                    return
                await asyncio.sleep(0.2)
                record["attempts"] += 1
                if record["attempts"] > max(record["max_retries"], 0):
                    self._complete_error(record, TaskError(
                        f"ActorUnavailableError: {record['name']} failed: {e}", "",
                        ActorUnavailableError(str(e))))
                    return
                task_events.record(
                    spec.task_id.hex(), task_events.RETRYING,
                    attempt=record["attempts"], error=f"actor push failed: {e}",
                    job_id=record.get("_job_hex", ""))
                continue
            if reply["status"] == "ok":
                self._process_reply_refs(reply, view.address)
                self._complete_ok(record, reply["results"],
                                  stream_count=reply.get("stream_count"))
            else:
                self._complete_error(record, loads_trusted(reply["error"]))
            return

    def stream_next(self, task_id: TaskID, index: int,
                    timeout: float = 3600.0):
        """Blocking wait for the index-th streamed return of a generator
        task; returns its ObjectRef, or raises StopIteration/the error."""
        tid_b = task_id.binary()

        async def _wait():
            deadline = time.monotonic() + timeout
            while True:
                st = self._streams.get(tid_b)
                if st is None:
                    return "stopped"
                if index < st["produced"]:
                    return "item"
                if st["error"] is not None:
                    return st["error"]
                if st["total"] is not None and index >= st["total"]:
                    return "stopped"
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise GetTimeoutError(
                        f"timed out waiting for streamed return {index}")
                try:
                    await asyncio.wait_for(
                        asyncio.shield(st["event"].wait()), remaining)
                except asyncio.TimeoutError:
                    pass

        out = self._run(_wait())
        if out == "item":
            oid = ObjectID.for_task_return(task_id, index)
            ref = ObjectRef(oid, self.address)
            # the ref now carries the count: hand over the arrival pin
            st = self._streams.get(tid_b)
            if st is not None and oid.binary() in st.get("pinned", set()):
                st["pinned"].discard(oid.binary())
                self.ref_counter.unpin(oid.binary())
            return ref
        if out == "stopped":
            raise StopIteration
        raise out  # the task's error

    def _chan_mailbox(self, name: str) -> dict:
        from collections import deque as _deque

        box = self._chan_mail.get(name)
        if box is None:
            box = self._chan_mail[name] = {
                "q": _deque(), "data": asyncio.Event(),
                "space": asyncio.Event(), "cap": 2, "last_seq": -1}
        return box

    def chan_pop(self, name: str, timeout: float = 300.0) -> bytes:
        """Reader side of a cross-host channel mailbox (blocking; called
        from the dag-loop/driver thread, never the io loop)."""
        async def _pop():
            box = self._chan_mailbox(name)
            deadline = time.monotonic() + timeout
            while not box["q"]:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"channel {name}: no value")
                try:
                    await asyncio.wait_for(box["data"].wait(),
                                           min(remaining, 5.0))
                except asyncio.TimeoutError:
                    pass
            blob = box["q"].popleft()
            ev, box["space"] = box["space"], asyncio.Event()
            ev.set()
            return blob

        return self._run(_pop(), timeout + 10.0)

    def chan_close(self, name: str):
        self._chan_mail.pop(name, None)
        self._chan_closed.add(name)
        if len(self._chan_closed) > 4096:
            self._chan_closed.pop()

    def stream_release(self, task_id: TaskID):
        """Generator handle dropped: release arrival pins for unconsumed
        items and forget the stream. Runs ON the io loop (scheduled from
        GC threads) so it cannot race the StreamTaskReturn handler's
        check-then-pin sequence and strand a pin forever."""
        def _do():
            st = self._streams.pop(task_id.binary(), None)
            if not st:
                return
            for oid_b in st.get("pinned", ()):
                try:
                    self.ref_counter.unpin(oid_b)
                except Exception as e:
                    logger.debug("stream unpin(%s) failed: %s",
                                 oid_b.hex()[:8], e)
            st["pinned"] = set()

        if threading.current_thread() is self._loop_thread:
            _do()
            return
        try:
            self.loop.call_soon_threadsafe(_do)
        except RuntimeError:
            _do()  # loop gone (shutdown): no handler left to race

    def get_actor(self, name: str, namespace: Optional[str] = None):
        from ray_tpu.actor import ActorHandle

        reply = self._run(self._gcs_call("GetNamedActor", {
            "name": name, "namespace": namespace or self.namespace}))
        info = reply["info"]
        if info is None:
            raise ValueError(f"no actor named {name!r}")
        aid = ActorID.from_hex(info["actor_id"])
        view = self._actor_view(aid)
        view.state, view.address = info["state"], info["address"]
        return ActorHandle(aid, (), info.get("class_name", ""))

    def get_actor_handle(self, actor_id: ActorID):
        from ray_tpu.actor import ActorHandle

        return ActorHandle(actor_id, (), "")

    def kill_actor(self, handle, no_restart=True):
        self._run(self._gcs_call("KillActor", {
            "actor_id": handle.actor_id.binary(), "no_restart": no_restart}))

    def cancel(self, ref, force=False, recursive=True):
        """Cancel a task (reference: CoreWorker::CancelTask paths in
        core_worker.cc). A still-queued task completes immediately with
        TaskCancelledError; a running task gets TaskCancelledError raised
        into its thread (cooperative), or its worker killed with
        force=True. Finished tasks are a no-op. Actor tasks: queued calls
        are dropped, running ASYNC calls are asyncio-cancelled, running
        sync calls get the cooperative async-exc; force=True is refused
        (matching the reference — it would kill the actor).
        ``recursive`` is accepted for API parity; this runtime does not
        track child-task trees. Accepts an ObjectRef or an
        ObjectRefGenerator (streaming task)."""
        from ray_tpu.object_ref import ObjectRefGenerator

        if isinstance(ref, ObjectRefGenerator):
            task_id = ref._task_id
        else:
            task_id = ref.id.task_id()
        self._run(self._cancel_async(task_id, force))

    async def _cancel_async(self, task_id: TaskID, force: bool):
        from ray_tpu.exceptions import TaskCancelledError

        rec = self._tasks.get(task_id) or self._actor_inflight.get(task_id)
        if rec is None:
            return  # finished-and-released or unknown: no-op
        if rec["spec"].actor_id is not None:
            # reference: CancelTask's actor path — queued calls are dropped,
            # running ASYNC calls are cancelled cooperatively; force-kill is
            # refused (it would take the whole actor down)
            if force:
                raise ValueError(
                    "force=True is not supported for actor tasks (it would "
                    "kill the actor); use ray_tpu.kill(actor) for that")
            if rec.get("_completed"):
                return
            rec["_cancelled"] = True
            addr = rec.get("_pushed_to")
            if addr:
                try:
                    await self._worker_client(addr).call(
                        "CancelTask", wire.dumps(
                            {"task_id": rec["spec"].task_id.binary(),
                             "force": False}), timeout=10.0, retries=1)
                except (RpcError, asyncio.TimeoutError, OSError) as e:
                    # actor death completes the call by itself
                    logger.debug("CancelTask to %s failed: %s", addr, e)
            return
        if rec.get("_completed"):
            return  # finished: never signal (or force-kill!) its worker
        rec["_cancelled"] = True
        # still queued in a lease pool: complete it right here
        for pool in self._lease_cache.values():
            if rec in pool.pending:
                try:
                    pool.pending.remove(rec)
                except ValueError:  # raylint: disable=EXC001 a concurrent grant already dequeued it; cancellation continues via the push path
                    break
                self._complete_error(rec, TaskCancelledError())
                return
        addr = rec.get("_pushed_to")
        if addr:
            try:
                await self._worker_client(addr).call(
                    "CancelTask", wire.dumps(
                        {"task_id": rec["spec"].task_id.binary(),
                         "force": force}), timeout=10.0, retries=1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                # worker already gone: the push failure completes it
                logger.debug("CancelTask(force=%s) to %s failed: %s",
                             force, addr, e)
        # else: awaiting dependency resolution — the resolver checks the
        # flag before the record can become push-eligible

    # ------------------------------------------------------------------
    # cluster info
    # ------------------------------------------------------------------

    def cluster_resources(self):
        return self._run(self._gcs_call("GetClusterResources", {}))["total"]

    def available_resources(self):
        return self._run(self._gcs_call("GetClusterResources", {}))["available"]

    def nodes(self):
        return self._run(self._gcs_call("GetAllNodes", {}))["nodes"]

    def get_state(self):
        return self._run(self._gcs_call("GetState", {}))

    # ------------------------------------------------------------------
    # executor side (reference: task_execution/task_receiver.cc)
    # ------------------------------------------------------------------

    async def _handle_rpc(self, method: str, payload: bytes, conn) -> bytes:
        if method == "PushTask":
            req = wire.loads(payload)
            return await self._handle_push_task(req["spec"])
        if method == "PushTaskBatch":
            import copy as _copy

            req = wire.loads(payload)
            if "items" in req:
                # template framing (owner warm path): decode each distinct
                # spec template once, then stamp per-task fields onto
                # shallow copies. The options object is shared across the
                # batch — execution only reads it.
                tmpls = [wire.loads(b) for b in req.get("templates", ())]
                specs = []
                for it in req["items"]:
                    if it[0] == "s":
                        specs.append(it[1])
                    else:
                        spec = _copy.copy(tmpls[it[1]])
                        spec.task_id = it[2]
                        spec.args_blob = it[3]
                        spec.attempt = it[4]
                        specs.append(spec)
            else:
                specs = req["specs"]
            results = []
            run: List[TaskSpec] = []  # consecutive plain tasks, fused

            async def _flush_run():
                if run:
                    results.extend(await self._exec_normal_batch(run))
                    run.clear()

            for spec in specs:
                if spec.actor_id is None and not spec.is_actor_creation \
                        and spec.num_returns != -1:
                    run.append(spec)
                else:
                    await _flush_run()
                    results.append(
                        wire.loads(await self._handle_push_task(spec)))
            await _flush_run()
            return wire.dumps({"results": results})
        if method == "GetOwnedObject":
            return await self._handle_get_owned(wire.loads(payload))
        if method == "ObjectLocAnnounce":
            # owner-resident directory write (reference:
            # ownership_object_directory.cc): raylets report seals of
            # objects this worker owns — batched per announce, same
            # attempt-fencing as the GCS directory. Best-effort: the GCS
            # keeps the durable copy.
            req = wire.loads(payload)
            tab = self._obj_locations
            attempt = req.get("attempt", 0)
            sizes = req.get("sizes") or {}
            node, addr = req["node_id"], req["address"]
            for ob in req["oids"]:
                entry = tab.get(ob)
                size = sizes.get(ob, 0) or 0
                if entry is None or attempt > entry["attempt"]:
                    tab[ob] = {"attempt": attempt, "size": size,
                               "nodes": {node: addr}}
                    if len(tab) > 65536:  # safety bound; GCS is fallback
                        tab.pop(next(iter(tab)))
                elif attempt == entry["attempt"]:
                    entry["nodes"][node] = addr
                    if size:
                        entry["size"] = size
            return wire.dumps({"status": "ok"})
        if method == "ObjectLocDrop":
            req = wire.loads(payload)
            entry = self._obj_locations.get(req["oid"])
            if entry is not None:
                entry["nodes"].pop(req["node_id"], None)
                if not entry["nodes"]:
                    self._obj_locations.pop(req["oid"], None)
            return wire.dumps({"status": "ok"})
        if method == "ObjectLocQuery":
            # owner-resident directory read: the pulling raylet asks the
            # owner, not the GCS (falls back there if we have nothing)
            req = wire.loads(payload)
            entry = self._obj_locations.get(req["oid"])
            if entry is None:
                return wire.dumps({"locations": [], "attempt": 0, "size": 0})
            return wire.dumps({
                "locations": [{"node_id": n, "address": a}
                              for n, a in entry["nodes"].items()],
                "attempt": entry["attempt"], "size": entry.get("size", 0)})
        if method == "AddBorrower":
            req = wire.loads(payload)
            self.ref_counter.add_borrower(req["oid"], req["address"])
            self._watch_borrower(req["oid"], req["address"])
            return wire.dumps({"status": "ok"})
        if method == "AddBorrowers":
            # bulk re-assert from a borrower's periodic sweep
            req = wire.loads(payload)
            for oid in req["oids"]:
                self.ref_counter.add_borrower(oid, req["address"])
                self._watch_borrower(oid, req["address"])
            return wire.dumps({"status": "ok"})
        if method == "WaitBorrowsDone":
            # borrower side of the owner's watch: long-poll until any of
            # the probed oids is fully released here
            req = wire.loads(payload)
            deadline = time.monotonic() + 25.0
            while True:
                self.ref_counter.flush_deletes()
                done = [o for o in req["oids"]
                        if self.ref_counter.held_count(o) <= 0]
                if done or self._shutdown or time.monotonic() > deadline:
                    return wire.dumps({"done": done})
                await asyncio.sleep(0.2)
        if method == "StreamTaskReturn":
            # executor pushing one streamed yield (reference: the dynamic
            # return objects a generator task reports to its owner)
            req = wire.loads(payload)
            tid_b = req["task_id"]
            rec = self._tasks.get(TaskID(tid_b))
            if rec is None:
                # actor streaming records live in the actor-inflight table
                rec = self._actor_inflight.get(TaskID(tid_b))
            if rec is not None and req.get("attempt", 0) != rec.get("epoch", 0):
                # zombie attempt: a retry superseded this execution — its
                # items must not interleave into the current stream
                return wire.dumps({"status": "stale_attempt"})
            oid = ObjectID.for_task_return(TaskID(tid_b), req["index"])
            if req["kind"] == "inline":
                inband, buffers = read_blob(req["blob"])
                self.memory_store[oid] = deserialize(inband, buffers)
            else:
                self._in_store[oid] = True
            if rec is not None and oid not in rec["return_ids"]:
                rec["return_ids"].append(oid)
            st = self._streams.get(tid_b)
            if st is not None:
                if oid.binary() not in st.setdefault("pinned", set()):
                    # pin until the consumer mints the ref (or the
                    # generator is released): completion must not free
                    # items the consumer has not reached yet
                    st["pinned"].add(oid.binary())
                    self.ref_counter.pin(oid.binary())
                st["produced"] = max(st["produced"], req["index"] + 1)
                ev, st["event"] = st["event"], asyncio.Event()
                ev.set()
            return wire.dumps({"status": "ok"})
        if method == "ChanPush":
            # cross-host channel leg: the WRITER pushes into a mailbox
            # hosted by this (reader) worker; a full mailbox parks the
            # push — that await IS the channel's backpressure
            req = wire.loads(payload)
            if req["name"] in self._chan_closed:
                # torn-down reader: drop the value instead of resurrecting
                # a mailbox nothing will ever pop again
                return wire.dumps({"status": "closed"})
            box = self._chan_mailbox(req["name"])
            seq = req.get("seq")
            if seq is not None and seq <= box["last_seq"]:
                # idempotent retry: the writer re-pushes after an ambiguous
                # RPC failure; a sequence it already delivered is acked
                # without enqueueing (never double-delivers)
                return wire.dumps({"status": "ok", "dup": True})
            deadline = time.monotonic() + 300.0
            while len(box["q"]) >= box["cap"]:
                if time.monotonic() > deadline or self._shutdown \
                        or req["name"] in self._chan_closed:
                    raise RpcError(f"channel {req['name']} reader stalled")
                try:
                    await asyncio.wait_for(box["space"].wait(), 5.0)
                except asyncio.TimeoutError:
                    pass
            if seq is not None:
                if seq <= box["last_seq"]:
                    # re-check after parking: a timed-out original and its
                    # retry can park concurrently on a full mailbox
                    return wire.dumps({"status": "ok", "dup": True})
                box["last_seq"] = seq
            box["q"].append(req["blob"])
            ev, box["data"] = box["data"], asyncio.Event()
            ev.set()
            return wire.dumps({"status": "ok"})
        if method == "CancelTask":
            # reference: HandleCancelTask — cooperative raise into the
            # executing thread, or force-exit the worker process
            req = wire.loads(payload)
            if req.get("force"):
                logger.warning("force-cancel: worker exiting")
                self.loop.call_later(0.05, os._exit, 1)
                return wire.dumps({"status": "ok"})
            from ray_tpu.exceptions import TaskCancelledError

            self._cancel_requested.add(req["task_id"])
            if len(self._cancel_requested) > 1024:
                self._cancel_requested.pop()
            atask = self._running_async_tasks.get(req["task_id"])
            if atask is not None:
                if not atask.done():
                    atask.cancel()
                return wire.dumps({"status": "ok"})
            ident = self._running_tasks.get(req["task_id"])
            if ident is not None:
                import ctypes

                n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_long(ident), ctypes.py_object(TaskCancelledError))
                if n != 1:  # thread already gone: fall back to the flag
                    logger.warning("cancel async-exc hit %d threads", n)
                    self._cancelled_pending.add(req["task_id"])
            else:
                self._cancelled_pending.add(req["task_id"])
            return wire.dumps({"status": "ok"})
        if method == "ProfileStacks":
            # py-spy-role stack sampling (dashboard/agent.py); runs in a
            # thread so the event loop keeps serving while sampling
            req = wire.loads(payload) or {}
            from ray_tpu.dashboard.agent import sample_stacks

            out = await asyncio.get_event_loop().run_in_executor(
                None, sample_stacks,
                float(req.get("duration_s", 2.0)),
                float(req.get("interval_ms", 10.0)))
            return wire.dumps(out)
        if method == "ProfileMemory":
            req = wire.loads(payload) or {}
            if not hasattr(self, "_mem_profiler"):
                from ray_tpu.dashboard.agent import MemoryProfiler

                self._mem_profiler = MemoryProfiler()
            action = req.get("action", "snapshot")
            if action == "start":
                out = self._mem_profiler.start(int(req.get("frames", 16)))
            elif action == "stop":
                out = self._mem_profiler.stop()
            else:
                out = self._mem_profiler.snapshot(int(req.get("top", 25)))
            return wire.dumps(out)
        if method == "Ping":
            return wire.dumps({"status": "ok", "pid": os.getpid()})
        if method == "GetDeviceObject":
            req = wire.loads(payload)
            value = self.device_store.get(req["oid"])
            if value is None and req["oid"] not in self.device_store:
                return wire.dumps({"status": "gone"})
            # large device->host copies must not stall the event loop
            self._ensure_pool(1)
            inband, buffers = await self.loop.run_in_executor(
                self._exec_pool, serialize, value)
            return wire.dumps({"status": "ok",
                                 "blob": pack_blob(inband, buffers)})
        if method == "FreeDeviceObject":
            req = wire.loads(payload)
            freed = self.device_store.pop(req["oid"], None) is not None
            return wire.dumps({"freed": freed})
        if method == "CheckActor":
            # GCS restart recovery probe: is the given actor instantiated
            # here? (dedups in-flight creations after an init-data replay)
            req = wire.loads(payload)
            hosting = (self.actor_instance is not None
                       and self.actor_id is not None
                       and self.actor_id.binary() == req["actor_id"])
            return wire.dumps({"hosting": hosting})
        raise RpcError(f"core worker: unknown method {method}")

    async def _handle_get_owned(self, req) -> bytes:
        oid = ObjectID(req["oid"])
        deadline = time.monotonic() + req.get("timeout", 10.0)
        if req.get("lost") and self._in_store.get(oid):
            # a borrower failed to pull a copy: verify against the directory
            # and reconstruct from lineage if it is really gone
            try:
                locs = await self._gcs_call("ObjectLocGet", {"oid": oid.binary()})
            except (RpcError, asyncio.TimeoutError, OSError):
                locs = {"locations": [None]}  # can't verify: assume alive
            if not locs["locations"]:
                self._in_store.pop(oid, None)
                if not await self._recover_object(oid):
                    err = ObjectLostError(
                        f"object {oid.hex()} lost and not reconstructable")
                    return wire.dumps({"status": "error",
                                         "error": pickle.dumps(err)})
        while True:
            if oid in self.memory_store:
                value = self.memory_store[oid]
                if isinstance(value, TaskError):
                    return wire.dumps({"status": "error", "error": pickle.dumps(value)})
                return wire.dumps({"status": "value",
                                     "blob": pack_blob(*serialize(value))})
            if self._in_store.get(oid):
                return wire.dumps({"status": "in_store"})
            fut = self._ensure_result_future(oid)
            if fut is not None and not fut.done() and time.monotonic() < deadline:
                try:
                    await asyncio.wait_for(asyncio.shield(fut),
                                           deadline - time.monotonic())
                except asyncio.TimeoutError:
                    pass
                continue
            if fut is None:
                # unknown everywhere: the object was freed (refs+borrowers
                # hit zero) or never existed — error beats an eternal poll
                err = ObjectLostError(
                    f"object {oid.hex()} was freed by its owner")
                return wire.dumps({"status": "error",
                                     "error": pickle.dumps(err)})
            return wire.dumps({"status": "pending"})

    async def _handle_push_task(self, spec: TaskSpec) -> bytes:
        if spec.is_actor_creation:
            return await self._exec_actor_creation(spec)
        if spec.actor_id is not None:
            return await self._exec_actor_task(spec)
        if spec.num_returns == -1:
            return await self._exec_streaming_task(spec)
        return await self._exec_normal_task(spec)

    def _ensure_pool(self, size: int, replace: bool = False):
        from concurrent.futures import ThreadPoolExecutor

        if self._exec_pool is None or (
                replace and self._exec_pool._max_workers < size):
            # a reused worker may carry a smaller pool from its task-executing
            # past; an actor with max_concurrency needs the full width
            self._exec_pool = ThreadPoolExecutor(max_workers=size,
                                                 thread_name_prefix="ray_tpu-exec")

    async def _exec_normal_task(self, spec: TaskSpec) -> bytes:
        if self.job_id.is_nil():
            self.job_id = spec.job_id
        fn = await self._fetch_function(spec.function_key)
        args, kwargs, seen_refs = await self._resolve_args(spec.args_blob)
        self._ensure_pool(1)
        t0 = time.time()
        result, err = await self.loop.run_in_executor(
            self._exec_pool, self._call_user_fn, fn, args, kwargs, spec)
        self._trace_task(spec, getattr(fn, "__name__", "task"), t0, err)
        del args, kwargs  # drop our handles before computing borrows
        return wire.dumps(await self._pack_results(
            spec, result, err, borrows=self._surviving_borrows(seen_refs)))

    async def _exec_normal_batch(self, specs: List[TaskSpec]) -> List[dict]:
        """Execute a run of plain tasks with ONE thread-pool hop. The
        per-task run_in_executor queue/GIL handoff costs ~0.5 ms on a
        small host — dominating trivial tasks — and the batch executes
        sequentially on the pool thread anyway (reference: leased workers
        run tasks serially, task_receiver.cc)."""
        if self.job_id.is_nil():
            self.job_id = specs[0].job_id
        prepared: List[tuple] = []  # (spec, fn, args, kwargs, seen) | (spec, TaskError)
        for spec in specs:
            try:
                fn = await self._fetch_function(spec.function_key)
                args, kwargs, seen = await self._resolve_args(spec.args_blob)
                prepared.append((spec, fn, args, kwargs, seen))
            except TaskError as e:
                # a PRODUCER's application error: deterministic, propagate
                # to this dependent as its own app error (no retry value)
                prepared.append((spec, e))
            # transient infra errors (object lost, owner unreachable, ...)
            # propagate and fail the whole RPC — the owner retries against
            # max_retries exactly like the unbatched path; nothing has
            # executed yet (prepare runs before _run_all), so no task
            # re-executes because of a batch-mate's infrastructure failure
        self._ensure_pool(1)

        def _run_all():
            out = []
            for i, entry in enumerate(prepared):
                if len(entry) == 2:
                    out.append(None)
                    continue
                spec, fn, args, kwargs, _seen = entry
                t0 = time.time()
                result, err = self._call_user_fn(fn, args, kwargs, spec)
                out.append((result, err, t0, time.time()))
                # drop the arg handles as each task finishes so its
                # surviving-borrow report below sees only real survivors
                prepared[i] = (spec, fn, None, None, _seen)
            return out

        outcomes = await self.loop.run_in_executor(self._exec_pool, _run_all)
        replies = []
        for entry, outcome in zip(prepared, outcomes):
            if outcome is None:
                replies.append({"status": "app_error",
                                "error": pickle.dumps(entry[1])})
                continue
            spec, fn, _a, _k, seen = entry
            result, err, t0, t1 = outcome
            self._trace_task(spec, getattr(fn, "__name__", "task"), t0, err,
                             t1=t1)
            replies.append(await self._pack_results(
                spec, result, err, borrows=self._surviving_borrows(seen)))
        return replies

    async def _exec_streaming_task(self, spec: TaskSpec) -> bytes:
        """num_returns="streaming": run the user generator, shipping each
        yield to the owner AS PRODUCED via StreamTaskReturn (awaited, so
        the stream is naturally 1-deep backpressured); the final reply
        carries the total count. Reference: the dynamic-returns generator
        protocol in task_manager.cc + generator_waiter.cc."""
        from ray_tpu.exceptions import TaskCancelledError

        if self.job_id.is_nil():
            self.job_id = spec.job_id
        fn = await self._fetch_function(spec.function_key)
        args, kwargs, seen_refs = await self._resolve_args(spec.args_blob)
        self._ensure_pool(1)
        owner = self._worker_client(spec.owner_address)
        tid_b = spec.task_id.binary()
        t0 = time.time()

        def _start():
            # cancellation registration is scoped to user-code execution
            # only (here and in _step): between steps this worker thread
            # runs OTHER work, and an async-exc into an ident not running
            # this task would cancel a stranger or kill the pool thread
            if tid_b in self._cancelled_pending:
                # raylint: disable=RCE001 set ops are single-bytecode; a cancel landing between the check and the discard is re-delivered via _cancel_requested's async-exc path
                self._cancelled_pending.discard(tid_b)
                return None, TaskCancelledError(
                    "TaskCancelledError: cancelled before execution", "")
            # raylint: disable=RCE002 dict set/get are single-bytecode; CancelTask missing a not-yet-registered ident falls back to _cancelled_pending, so a stale read only defers the cancel
            self._running_tasks[tid_b] = threading.get_ident()
            token = self._obs_task_start(spec)
            try:
                return fn(*args, **kwargs), None
            except Exception as e:
                return None, TaskError(repr(e), traceback.format_exc())
            finally:
                self._obs_task_end(token)
                self._running_tasks.pop(tid_b, None)

        gen, err = await self.loop.run_in_executor(self._exec_pool, _start)
        if err is None and not hasattr(gen, "__next__"):
            err = TaskError(
                f"num_returns='streaming' task {spec.function_key[:12]} did "
                f"not return a generator (got {type(gen).__name__})", "")
        index = 0
        while err is None:
            def _step():
                if tid_b in self._cancelled_pending:
                    self._cancelled_pending.discard(tid_b)
                    return None, True, TaskCancelledError()
                self._running_tasks[tid_b] = threading.get_ident()
                token = self._install_trace(spec)
                try:
                    return next(gen), False, None
                except StopIteration:
                    return None, True, None
                except TaskCancelledError as e:
                    return None, True, e
                except Exception as e:
                    return None, True, TaskError(repr(e),
                                                 traceback.format_exc())
                finally:
                    self._obs_task_end(token)
                    self._running_tasks.pop(tid_b, None)
            value, done, err = await self.loop.run_in_executor(
                self._exec_pool, _step)
            if done:
                break
            oid = ObjectID.for_task_return(spec.task_id, index)
            inband, buffers = serialize(value)
            total = len(inband) + sum(b.nbytes for b in buffers)
            if total < RAY_CONFIG.object_inline_max_bytes:
                payload = {"task_id": tid_b, "index": index,
                           "kind": "inline", "attempt": spec.attempt,
                           "blob": pack_blob(inband, buffers)}
            else:
                await self._store_blob(oid, inband, buffers, spec.attempt,
                                       owner=spec.owner_address)
                payload = {"task_id": tid_b, "index": index,
                           "kind": "store", "attempt": spec.attempt}
            await owner.call("StreamTaskReturn", wire.dumps(payload),
                             timeout=60.0, retries=2)
            index += 1
        self._trace_task(spec, getattr(fn, "__name__", "stream"), t0, err)
        del args, kwargs, gen
        if err is not None:
            return wire.dumps({"status": "app_error",
                                 "error": pickle.dumps(err)})
        reply = await self._pack_results(
            spec, None, None, borrows=self._surviving_borrows(seen_refs))
        reply["stream_count"] = index
        return wire.dumps(reply)

    async def _exec_actor_streaming(self, spec: TaskSpec, method, args,
                                    kwargs, seen_refs) -> bytes:
        """Streaming actor method (num_returns="streaming"): same yield-by-
        yield StreamTaskReturn protocol as task generators, for sync AND
        async generator methods — async generators stream straight off the
        actor's event loop under the concurrency semaphore (the shape LLM
        token streaming needs). Reference: dynamic returns for actor tasks
        in task_manager.cc + serve's streaming replica handlers."""
        import inspect

        from ray_tpu.exceptions import TaskCancelledError

        owner = self._worker_client(spec.owner_address)
        tid_b = spec.task_id.binary()
        t0 = time.time()
        index = 0
        err = None

        async def _ship(value, index):
            oid = ObjectID.for_task_return(spec.task_id, index)
            inband, buffers = serialize(value)
            total = len(inband) + sum(b.nbytes for b in buffers)
            if total < RAY_CONFIG.object_inline_max_bytes:
                payload = {"task_id": tid_b, "index": index,
                           "kind": "inline", "attempt": spec.attempt,
                           "blob": pack_blob(inband, buffers)}
            else:
                await self._store_blob(oid, inband, buffers, spec.attempt,
                                       owner=spec.owner_address)
                payload = {"task_id": tid_b, "index": index,
                           "kind": "store", "attempt": spec.attempt}
            await owner.call("StreamTaskReturn", wire.dumps(payload),
                             timeout=60.0, retries=2)

        if inspect.isasyncgenfunction(method):
            async with self._actor_sem:
                obs_token = self._obs_task_start(spec)
                try:
                    agen = method(*args, **kwargs)
                    async for value in agen:
                        if tid_b in self._cancelled_pending:
                            self._cancelled_pending.discard(tid_b)
                            err = TaskCancelledError()
                            await agen.aclose()
                            break
                        await _ship(value, index)
                        index += 1
                except TaskCancelledError as e:
                    err = e
                except Exception as e:
                    err = TaskError(repr(e), traceback.format_exc())
                finally:
                    self._obs_task_end(obs_token)
        else:
            self._ensure_pool(1)

            def _start():
                token = self._obs_task_start(spec)
                try:
                    out = method(*args, **kwargs)
                    if not hasattr(out, "__next__"):
                        return None, TaskError(
                            f"streaming actor method {spec.method_name} did "
                            f"not return a generator "
                            f"(got {type(out).__name__})", "")
                    return out, None
                except Exception as e:
                    return None, TaskError(repr(e), traceback.format_exc())
                finally:
                    self._obs_task_end(token)

            gen, err = await self.loop.run_in_executor(self._exec_pool, _start)
            while err is None:
                def _step():
                    if tid_b in self._cancelled_pending:
                        self._cancelled_pending.discard(tid_b)
                        return None, True, TaskCancelledError()
                    token = self._install_trace(spec)
                    try:
                        return next(gen), False, None
                    except StopIteration:
                        return None, True, None
                    except Exception as e:
                        return None, True, TaskError(repr(e),
                                                     traceback.format_exc())
                    finally:
                        self._obs_task_end(token)

                value, done, err = await self.loop.run_in_executor(
                    self._exec_pool, _step)
                if done:
                    break
                await _ship(value, index)
                index += 1
            del gen
        self._trace_task(spec, spec.method_name, t0, err)
        del args, kwargs
        if err is not None:
            return wire.dumps({"status": "app_error",
                               "error": pickle.dumps(err)})
        reply = await self._pack_results(
            spec, None, None, borrows=self._surviving_borrows(seen_refs))
        reply["stream_count"] = index
        return wire.dumps(reply)

    def _install_trace(self, spec: TaskSpec):
        """Install this task's span as the active trace context (so nested
        ``.remote()`` calls and ``tracing.profile()`` blocks parent onto
        it); returns a reset token, or None when tracing is off."""
        from ray_tpu.util import tracing

        if not tracing.enabled() or not spec.trace_id:
            return None
        return tracing.set_context(spec.trace_id, _task_span_id(spec))

    def _obs_task_start(self, spec: TaskSpec):
        """Execution-start observability: a RUNNING lifecycle event plus
        trace-context install. Returns the trace token for _obs_task_end."""
        if task_events.enabled():
            task_events.record(
                spec.task_id.hex(), task_events.RUNNING,
                attempt=spec.attempt, job_id=spec.job_id.hex(),
                worker=self.address, node=self.node_hex,
                span_id=_task_span_id(spec))
        return self._install_trace(spec)

    def _obs_task_end(self, token):
        if token is not None:
            from ray_tpu.util import tracing

            tracing.reset_context(token)

    def _trace_task(self, spec: TaskSpec, name: str, t0: float, err,
                    t1: Optional[float] = None):
        """Per-executed-task exec-latency metric (always on) + trace span
        (reference: profile_event.cc into the task event buffer); the span
        carries the task's causal ids so export_chrome_trace can draw the
        submit→execute flow arrow."""
        end = t1 if t1 is not None else time.time()
        if spec.actor_id is not None and spec.method_name:
            name = f"{type(self.actor_instance).__name__}.{spec.method_name}"                 if self.actor_instance is not None else spec.method_name
        try:
            _obs()["exec"].observe(end - t0, tags={"function": name})
        except Exception:  # raylint: disable=EXC001 metrics must never fail task execution
            pass
        from ray_tpu.util import tracing

        if not tracing.enabled():
            return
        extra = {}
        if spec.trace_id:
            extra = {"trace_id": spec.trace_id,
                     "span_id": _task_span_id(spec),
                     "parent_id": spec.parent_span_id or None}
        tracing.record_span(
            name, t0, end,
            category="actor_task" if spec.actor_id is not None else "task",
            task_id=spec.task_id.hex(), ok=err is None, **extra)

    def _call_user_fn(self, fn, args, kwargs, spec: TaskSpec):
        from ray_tpu.exceptions import TaskCancelledError

        tid_b = spec.task_id.binary()
        if tid_b in self._cancelled_pending:
            self._cancelled_pending.discard(tid_b)
            return None, TaskCancelledError(
                "TaskCancelledError: cancelled before execution started", "")
        self._running_tasks[tid_b] = threading.get_ident()
        self._tls.task_id = spec.task_id
        obs_token = self._obs_task_start(spec)
        try:
            result = fn(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = asyncio.run(result)
            return result, None
        except TaskCancelledError as e:
            if tid_b not in self._cancel_requested:
                # an async-exc aimed at the PREVIOUS task on this thread
                # landed late (delivery is deferred to a bytecode check):
                # this task is an innocent victim — report it as a worker-
                # side interruption the owner retries, not a cancellation
                from ray_tpu.exceptions import StrayInterrupt

                logger.warning("stray cancellation landed in task %s",
                               spec.task_id.hex()[:12])
                return None, TaskError(
                    "task interrupted by a stray cancellation "
                    "(async-exc delivery race); retryable", "",
                    cause=StrayInterrupt())
            # raylint: disable=RCE001 set add/discard are single-bytecode; the cancel handshake tolerates either ordering (a late cancel is absorbed by the stray-interrupt retry path above)
            self._cancel_requested.discard(tid_b)
            return None, e
        except Exception as e:
            return None, TaskError(repr(e), traceback.format_exc())
        finally:
            self._obs_task_end(obs_token)
            self._running_tasks.pop(tid_b, None)
            self._tls.task_id = None

    async def _resolve_args(self, args_blob: bytes):
        from ray_tpu.object_ref import collect_deserialized_refs

        inband, buffers = read_blob(args_blob)
        with collect_deserialized_refs() as seen_refs:
            args, kwargs = deserialize(inband, buffers)

        async def _resolve(v):
            if isinstance(v, ObjectRef):
                # task-arg pulls rank below blocked gets at the raylet's
                # pull admission (reference: pull_manager.cc classes)
                value = await self._get_one(
                    v, time.monotonic() + RAY_CONFIG.object_pull_timeout_s,
                    prio=1)
                if isinstance(value, TaskError):
                    raise value
                return await self._maybe_pull_device(
                    value, time.monotonic() + RAY_CONFIG.object_pull_timeout_s)
            return v

        args = [await _resolve(a) for a in args]
        kwargs = {k: await _resolve(v) for k, v in kwargs.items()}
        return args, kwargs, seen_refs

    def _surviving_borrows(self, seen_refs):
        """Foreign refs from the args that are still held in this process
        after execution — reported on the reply so the owner registers this
        worker as a borrower (reference: GetAndClearBorrowedRefs)."""
        # the `del args, kwargs` decrements are still queued on the __del__-
        # safe deletion queue: flush them first, or every arg ref would
        # report as still held and pin its object on the owner forever
        self.ref_counter.flush_deletes()
        out = []
        for oid, owner in {(o, w) for o, w in seen_refs}:
            if owner and owner != self.address \
                    and self.ref_counter.held_count(oid) > 0:
                out.append((oid, owner))
        return out

    async def _pack_results(self, spec: TaskSpec, result, err,
                            transport: str = "", borrows=()) -> dict:
        """Build one task's reply dict (callers pickle it, or embed it
        directly in a batch reply — no per-task double pickling)."""
        if err is not None:
            return {"status": "app_error", "error": pickle.dumps(err)}
        values: List[Any]
        if spec.num_returns <= 0:  # 0 returns, or -1 = streaming (items
            values = []            # already shipped via StreamTaskReturn)
        elif spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                err = TaskError(
                    f"task declared num_returns={spec.num_returns} but returned "
                    f"{len(values)} values", "")
                return {"status": "app_error", "error": pickle.dumps(err)}
        from ray_tpu.object_ref import collect_serialized_refs

        results = []
        nested: Dict[bytes, list] = {}
        for i, value in enumerate(values):
            oid = ObjectID.for_task_return(spec.task_id, i)
            if transport:
                # the value stays resident here; ship a small marker instead
                from ray_tpu.experimental.device_objects import DeviceObjectMarker

                self.device_store[oid.binary()] = value
                value = DeviceObjectMarker(oid.binary(), self.address, transport)
            with collect_serialized_refs() as inner:
                inband, buffers = serialize(value)
            total = len(inband) + sum(b.nbytes for b in buffers)
            if total < RAY_CONFIG.object_inline_max_bytes:
                results.append(("inline", pack_blob(inband, buffers)))
                # inline values are rehydrated in the owner's memory store;
                # the live inner refs there carry the counts
            else:
                await self._store_blob(oid, inband, buffers, spec.attempt,
                                       owner=spec.owner_address)
                # the size annotation feeds the owner's per-task
                # returned-object-bytes accounting (task events)
                results.append(("store", total))
                if inner:
                    # stored blobs hold refs only as bytes: the owner must
                    # pin them for the blob's lifetime
                    nested[oid.binary()] = inner
        return {"status": "ok", "results": results,
                "borrows": list(borrows), "nested": nested}

    async def _exec_actor_creation(self, spec: TaskSpec) -> bytes:
        if self.job_id.is_nil():
            self.job_id = spec.job_id
        cls = await self._fetch_function(spec.function_key)
        args, kwargs, _seen = await self._resolve_args(spec.args_blob)
        opts = spec.actor_options
        self._ensure_pool(max(1, opts.max_concurrency), replace=True)
        self.actor_id = spec.actor_id

        def _create():
            try:
                # raylint: disable=RCE002 CheckActor tolerates a stale None (reports not-ready); task dispatch reads only after the creation reply, ordered by run_in_executor's future
                self.actor_instance = cls(*args, **kwargs)
                return None
            except Exception as e:
                return TaskError(repr(e), traceback.format_exc())

        err = await self.loop.run_in_executor(self._exec_pool, _create)
        if err is not None:
            return wire.dumps({"status": "app_error", "error": pickle.dumps(err)})
        self._actor_async = any(
            asyncio.iscoroutinefunction(getattr(self.actor_instance, n, None))
            for n in dir(self.actor_instance) if not n.startswith("__"))
        self._actor_sem = asyncio.Semaphore(max(1, opts.max_concurrency))
        return wire.dumps({"status": "ok", "results": []})

    async def _wait_for_turn(self, spec: TaskSpec):
        """Per-caller seqno ordering (reference: actor_scheduling_queue.cc):
        start tasks in submission order. A missing seqno (failed send)
        stalls successors only for a bounded grace period, after which the
        gap is ABANDONED: a predecessor arriving later is rejected as
        stale (the owner retries it under a fresh seqno) rather than
        silently executed out of order."""
        from ray_tpu.exceptions import TaskError as _TaskError

        state = self._order_buf.setdefault(
            spec.owner_address, {"expected": 1, "events": {}})
        if spec.seqno < state["expected"]:
            raise _TaskError(
                f"stale actor-task seqno {spec.seqno} (queue already at "
                f"{state['expected']}): an abandoned ordering gap — "
                f"resubmit under a fresh seqno", "")
        if spec.seqno > state["expected"]:
            ev = state["events"].setdefault(spec.seqno, asyncio.Event())
            try:
                # bounded grace: a gap (lost predecessor) must not wedge
                # the queue forever
                await asyncio.wait_for(ev.wait(), timeout=30.0)
            except asyncio.TimeoutError:
                logger.warning(
                    "actor queue abandoning ordering gap before seqno %d "
                    "(predecessor lost?)", spec.seqno)
        state["expected"] = max(state["expected"], spec.seqno + 1)
        nxt = state["events"].pop(state["expected"], None)
        if nxt is not None:
            nxt.set()

    async def _run_actor_coro(self, method, args, kwargs, spec: TaskSpec):
        """Async actor method under this task's observability context: the
        RUNNING event and trace install happen inside the child task, so the
        contextvar scope dies with it and never leaks onto the loop."""
        token = self._obs_task_start(spec)
        try:
            return await method(*args, **kwargs)
        finally:
            self._obs_task_end(token)

    async def _exec_actor_task(self, spec: TaskSpec) -> bytes:
        if self.actor_instance is None:
            err = TaskError("ActorUnavailableError: actor instance not initialized", "")
            return wire.dumps({"status": "app_error", "error": pickle.dumps(err)})
        if spec.seqno > 0:
            await self._wait_for_turn(spec)
        if spec.method_name == "__rtpu_dag_loop__":
            # compiled-graph data plane: install this actor's static schedule
            # and run it on a dedicated thread — no further control-plane
            # traffic per iteration (reference: dag_node_operation.py:704)
            from ray_tpu.dag.executor import DagLoopRunner

            args, kwargs, _seen = await self._resolve_args(spec.args_blob)
            try:
                runner = DagLoopRunner(self.actor_instance, args[0])
                runner.start()
                self._dag_runner = runner  # keep alive with the actor
            except Exception as e:
                err = TaskError(repr(e), traceback.format_exc())
                return wire.dumps({"status": "app_error",
                                     "error": pickle.dumps(err)})
            return wire.dumps({"status": "ok", "results": [
                ("inline", pack_blob(*serialize("started")))]})
        method = getattr(self.actor_instance, spec.method_name, None)
        if method is None:
            err = TaskError(f"AttributeError: no method {spec.method_name}", "")
            return wire.dumps({"status": "app_error", "error": pickle.dumps(err)})
        # per-call options win over the decorator; "object" forces the
        # plain object-plane return (reference: ray.method override order)
        transport = (getattr(spec, "tensor_transport", "")
                     or getattr(method, "__ray_tpu_tensor_transport__", ""))
        if transport == "object":
            transport = ""
        args, kwargs, seen_refs = await self._resolve_args(spec.args_blob)
        if spec.num_returns == -1:
            return await self._exec_actor_streaming(
                spec, method, args, kwargs, seen_refs)
        t0 = time.time()
        if asyncio.iscoroutinefunction(method):
            from ray_tpu.exceptions import TaskCancelledError

            tid_b = spec.task_id.binary()
            async with self._actor_sem:
                if tid_b in self._cancelled_pending:
                    # cancelled while queued behind the concurrency cap
                    self._cancelled_pending.discard(tid_b)
                    result, err = None, TaskCancelledError(
                        "TaskCancelledError: cancelled before execution", "")
                else:
                    # run as a child task so CancelTask can .cancel() it
                    # without touching this RPC handler (reference:
                    # async-actor cooperative cancellation)
                    atask = asyncio.ensure_future(
                        self._run_actor_coro(method, args, kwargs, spec))
                    self._running_async_tasks[tid_b] = atask
                    try:
                        result, err = await atask, None
                    except asyncio.CancelledError:
                        result, err = None, TaskCancelledError()
                    except Exception as e:
                        result, err = None, TaskError(repr(e),
                                                      traceback.format_exc())
                    finally:
                        self._running_async_tasks.pop(tid_b, None)
        else:
            result, err = await self.loop.run_in_executor(
                self._exec_pool, self._call_user_fn, method, args, kwargs, spec)
        self._trace_task(spec, spec.method_name, t0, err)
        del args, kwargs  # drop our handles before computing borrows
        return wire.dumps(await self._pack_results(
            spec, result, err, transport=transport,
            borrows=self._surviving_borrows(seen_refs)))

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        from ray_tpu import object_ref as object_ref_mod

        if getattr(object_ref_mod, "_ref_counter", None) is self.ref_counter:
            object_ref_mod.set_ref_counter(None)
        try:
            from ray_tpu.util import tracing

            if tracing.enabled():
                tracing.flush()
        except Exception as e:
            logger.debug("tracing flush at shutdown failed: %s", e)
        try:
            # tail-event protection: events recorded since the last flush
            # interval must not die with the process
            task_events.flush()
        except Exception as e:
            logger.debug("task-event flush at shutdown failed: %s", e)
        for fut_name in ("_obs_fut", "_sweep_fut"):
            fut = getattr(self, fut_name, None)
            if fut is not None:
                fut.cancel()

        async def _close():
            if self.server:
                await self.server.stop()
            if self.gcs:
                await self.gcs.close()
            for c in list(self._raylet_clients.values()) + list(self._worker_clients.values()):
                await c.close()
            if self.raylet:
                await self.raylet.close()

        try:
            self._run(_close(), timeout=10.0)
        except Exception as e:
            logger.debug("rpc client close at shutdown failed: %s", e)
        if self._owned_loop:
            self.loop.call_soon_threadsafe(self.loop.stop)
            if self._loop_thread:
                self._loop_thread.join(timeout=5.0)
        self.segments.clear()


# ---------------------------------------------------------------------------
# driver bootstrap
# ---------------------------------------------------------------------------


class DriverWorker(CoreWorker):
    """Driver facade: also owns the locally-started cluster, if any."""

    def __init__(self, *args, node_supervisor=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.node_supervisor = node_supervisor
        self.current_task_id = None
        self.current_actor_id = None

    def shutdown(self):
        super().shutdown()
        if self.node_supervisor is not None:
            self.node_supervisor.stop()
            self.node_supervisor = None


def connect_driver(address, num_cpus, num_tpus, resources, labels, namespace,
                   object_store_memory, log_to_driver,
                   include_dashboard=False, dashboard_port=None):
    supervisor = None
    dashboard_address = ""
    if address is None:
        from ray_tpu._private.node import NodeSupervisor

        node_res = dict(resources or {})
        if num_cpus is not None:
            node_res["CPU"] = float(num_cpus)
        if num_tpus is not None:
            node_res["TPU"] = float(num_tpus)
        supervisor = NodeSupervisor(resources=node_res, labels=labels,
                                    object_store_memory=object_store_memory)
        address = supervisor.start_head()
        if include_dashboard:
            dashboard_address = supervisor.start_dashboard(port=dashboard_port)
            logger.info("dashboard at http://%s", dashboard_address)
    elif include_dashboard:
        logger.warning(
            "include_dashboard=True is ignored when connecting to an "
            "existing cluster (%s); start one on the head node with "
            "`ray-tpu start --include-dashboard` instead", address)
    worker = DriverWorker(
        gcs_address=address,
        raylet_address=None,
        node_id=None,
        is_driver=True,
        namespace=namespace,
        node_supervisor=supervisor,
    )
    worker.dashboard_address = dashboard_address
    worker.log_to_driver = bool(log_to_driver)
    worker.connect()
    return worker

"""Raylet: the per-node daemon.

Reference: ``src/ray/raylet`` — ``NodeManager`` (node_manager.h:133) handling
worker-lease requests (node_manager.cc:1820), the ``WorkerPool``
(worker_pool.h:276) that spawns/reuses worker processes, placement-group
bundle accounting (placement_group_resource_manager.cc), worker-death
detection, and the node object plane: it hosts the shared-memory object store
(plasma ``store_runner.cc``) and the pull/push transfer manager
(``object_manager/pull_manager.cc``).

Two-level scheduling (reference: cluster_lease_manager.cc:196 grant-or-
spillback at :421): plain lease requests go to the OWNER'S LOCAL raylet,
which grants from its pool or replies ``spillback`` with a peer chosen from
its synced cluster resource view — no per-lease GCS round trip. The view is
maintained by subscribing to the GCS ``resource_view`` delta stream
(reference: ray_syncer.h:89); placement-group and strategy-pinned leases
still resolve through the GCS (`PickNode`), as does the infeasible fallback
that feeds autoscaler demand.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle

from ray_tpu._private import wire
from ray_tpu.exceptions import RuntimeEnvSetupError
import signal
import subprocess
import sys
import time
import uuid
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.common import (
    NodeInfo,
    label_match,
    resources_add,
    resources_ge,
    resources_sub,
)
from ray_tpu._private.async_util import spawn
from ray_tpu._private.config import RAY_CONFIG
from ray_tpu._private.ids import NodeID
from ray_tpu._private.object_store import ObjectStoreServer
from ray_tpu._private.provisioner import WorkerProvisioner
from ray_tpu._private.provisioner.pool import _obs as _pool_obs
from ray_tpu._private.rpc import RpcError, RpcServer, RetryingRpcClient

logger = logging.getLogger("ray_tpu.raylet")


class _PullRetry(Exception):
    """Internal: the chosen pull source had no usable copy; re-pick."""


class WorkerProc:
    def __init__(self, proc: subprocess.Popen, renv_hash: str = ""):
        self.proc = proc
        self.pid = proc.pid
        self.address = ""
        self.registered = asyncio.get_event_loop().create_future()
        self.job_hex: Optional[str] = None
        self.renv_hash = renv_hash  # workers are dedicated to one runtime env
        self.leases: Set[str] = set()
        self.idle_since = time.monotonic()
        self.started = time.monotonic()
        # refreshed on every lease grant: the OOM victim policy ranks by
        # work-assignment recency, not process age (reused workers are old
        # processes that may hold the newest work)
        self.last_assigned = time.monotonic()
        self.client: Optional[RetryingRpcClient] = None


class Raylet:
    def __init__(
        self,
        gcs_address: str,
        node_id: Optional[NodeID] = None,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        is_head: bool = False,
        host: str = "127.0.0.1",
        port: int = 0,
        log_dir: str = "",
        object_store_memory: Optional[int] = None,
    ):
        self.node_id = node_id or NodeID.from_random()
        self.gcs_address = gcs_address
        self.is_head = is_head
        self.log_dir = log_dir
        self.server = RpcServer(self._handle, host, port)
        self.gcs = RetryingRpcClient(gcs_address, on_push=self._on_gcs_push,
                                     on_reconnect=self._on_gcs_reconnect)
        # synced view of peer nodes (node_hex -> {address, available, total,
        # labels, alive}) fed by the GCS resource_view delta stream
        self.cluster_view: Dict[str, dict] = {}
        # parked lease shapes (req_id -> {resources, selector}) reported on
        # heartbeats as autoscaler demand
        self._parked: Dict[str, dict] = {}
        # OOM defense: workers killed by the memory monitor, so owners can
        # surface OutOfMemoryError instead of a generic worker death
        self.oom_kills: Dict[str, float] = {}  # worker_address -> kill ts
        self.total_resources = dict(resources or {})
        self.available = dict(self.total_resources)
        self.labels = dict(labels or {})
        self.store = ObjectStoreServer(self.node_id.hex(), object_store_memory)
        self.workers: Dict[int, WorkerProc] = {}  # pid -> proc
        self.workers_by_addr: Dict[str, WorkerProc] = {}
        self.idle_workers: List[WorkerProc] = []
        self.leases: Dict[str, Tuple[WorkerProc, Dict[str, float], Optional[bytes]]] = {}
        # pg_id bytes -> bundle_idx -> (reserved, available)
        self.pg_reserved: Dict[bytes, Dict[int, Dict[str, float]]] = {}
        self.pg_available: Dict[bytes, Dict[int, Dict[str, float]]] = {}
        self.pg_committed: Set[bytes] = set()
        self._lease_waiters: List[asyncio.Future] = []
        self._pulls: Dict[bytes, asyncio.Task] = {}
        self._background: List[asyncio.Task] = []
        self._spawn_env = dict(os.environ)
        # children verify this at startup (die_with_parent window check)
        self._spawn_env["RAY_TPU_PARENT_PID"] = str(os.getpid())
        self._spawn_sem = asyncio.Semaphore(
            max(1, RAY_CONFIG.worker_startup_concurrency))
        # provisioning plane: zygote prefork pool + warm replenishment
        # (reference: worker_pool.h prestart/adoption)
        self.provisioner = WorkerProvisioner(self)
        # bounded concurrent inbound pulls (reference: pull_manager.cc's
        # prioritized admission; FIFO here — all pulls are one class)
        from ray_tpu._private.pull_manager import PullQueue

        self._pull_queue = PullQueue(
            max(1, RAY_CONFIG.object_pull_concurrency),
            stale_ttl_s=RAY_CONFIG.object_pull_interest_ttl_s)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> str:
        addr = await self.server.start()
        if "CPU" not in self.total_resources:
            self.total_resources["CPU"] = float(os.cpu_count() or 1)
            self.available["CPU"] = self.total_resources["CPU"]
        self._detect_tpu()
        info = NodeInfo(
            node_id=self.node_id,
            address=addr,
            object_store_address=addr,
            total_resources=dict(self.total_resources),
            labels=dict(self.labels),
            is_head=self.is_head,
        )
        await self.gcs.call("RegisterNode", wire.dumps({"info": info}))
        await self._subscribe_view()
        # zygote boot (preimports the heavy stack) runs in the background:
        # the raylet must register + serve immediately; fork requests wait
        # for readiness inside the provisioner instead
        self._background.append(spawn(self.provisioner.start(),
                                      what="zygote start"))
        self._background.append(spawn(self._heartbeat_loop(),
                                      what="raylet heartbeat loop"))
        self._background.append(spawn(self._metrics_loop(),
                                      what="raylet metrics loop"))
        self._background.append(spawn(self._monitor_workers_loop(),
                                      what="worker monitor loop"))
        self._background.append(spawn(self._memory_monitor_loop(),
                                      what="memory monitor loop"))
        self._background.append(spawn(self._prestart_workers(),
                                      what="worker prestart"))
        self._background.append(spawn(self.provisioner.replenish_loop(),
                                      what="warm-pool replenish loop"))
        self._background.append(spawn(self._prewarm_store(),
                                      what="store prewarm"))
        if self.log_dir:
            self._background.append(spawn(self._log_monitor_loop(),
                                          what="log monitor loop"))
        logger.info("raylet %s on %s resources=%s", self.node_id.hex()[:8], addr,
                    self.total_resources)
        return addr

    def _detect_tpu(self):
        """TPU chip/slice detection (reference: _private/accelerators/tpu.py)."""
        from ray_tpu.util.accelerators import detect_tpu

        chips, tpu_labels = detect_tpu()
        if chips and "TPU" not in self.total_resources:
            self.total_resources["TPU"] = float(chips)
            self.available["TPU"] = float(chips)
        for k, v in tpu_labels.items():
            self.labels.setdefault(k, v)

    async def stop(self):
        for t in self._background:
            t.cancel()
        await self.provisioner.close()
        for w in list(self.workers.values()):
            try:
                w.proc.kill()
            except Exception as e:
                logger.debug("kill of worker pid %s at stop failed: %s",
                             w.pid, e)
        self.store.shutdown()
        await self.server.stop()

    async def _subscribe_view(self, client=None):
        """Subscribe to the resource_view delta stream and seed the local
        cluster view (reference: ray_syncer snapshot + deltas). Re-run on
        every reconnect: deltas published during a disconnect are lost, and
        a node that died in that window never heartbeats again, so only a
        fresh snapshot can correct the view."""
        client = client or self.gcs
        await client.call("Subscribe", wire.dumps(
            {"channels": ["resource_view"]}))
        reply = wire.loads(await client.call("GetAllNodes", b""))
        for n in reply["nodes"]:
            self.cluster_view[n["node_id"]] = {
                "address": n["address"],
                "available": n.get("available", {}),
                "total": n["total_resources"],
                "labels": n.get("labels", {}),
                "alive": n.get("alive", True),
            }

    def _on_gcs_push(self, channel: str, payload: bytes):
        if channel != "resource_view":
            return
        msg = wire.loads(payload)
        # one publish per GCS tick carries every dirty node's latest view
        # ("views" batch); entries are idempotent last-writer-wins, so the
        # legacy single-entry form stays accepted
        for m in msg["views"] if "views" in msg else (msg,):
            # raylint: disable=RCE001 _on_gcs_push is registered as the client's push callback and always fires on this raylet's loop; the dynamic registration is invisible to the call graph, so it defaults to the caller thread
            self.cluster_view[m["node_id"]] = {
                "address": m["address"], "available": m["available"],
                "total": m["total"], "labels": m["labels"],
                "alive": m["alive"],
            }

    async def _on_gcs_reconnect(self, client):
        try:
            await self._subscribe_view(client)
        except Exception:
            logger.warning("resource_view re-subscribe failed", exc_info=True)

    def _pick_spill_node(self, resources, selector,
                         require_available: bool = True,
                         locality: Optional[Dict[str, int]] = None
                         ) -> Optional[str]:
        """Choose a peer raylet for spillback from the synced view (hybrid
        policy: pack onto the most-utilized feasible peer below the spread
        threshold, else the least utilized; reference:
        policy/hybrid_scheduling_policy.cc)."""
        me = self.node_id.hex()
        candidates = []
        for hex_id, v in self.cluster_view.items():
            if hex_id == me or not v["alive"]:
                continue
            if selector and not label_match(v.get("labels", {}), selector):
                continue
            pool = v["available"] if require_available else v["total"]
            if not resources_ge(pool, resources):
                continue
            fracs = [1.0 - v["available"].get(k, 0.0) / t
                     for k, t in v["total"].items() if t > 0]
            candidates.append((max(fracs) if fracs else 0.0, hex_id,
                               v["address"]))
        if not candidates:
            return None
        candidates.sort()
        threshold = RAY_CONFIG.scheduler_spread_threshold
        packed = [c for c in candidates if c[0] < threshold]
        if locality:
            # among below-threshold peers, prefer the one already holding
            # the most argument bytes (reference: locality-aware lease
            # policy, task_submission/lease_policy.cc): the pull it saves
            # usually dwarfs a small utilization difference
            pool = packed or candidates
            best = max(pool, key=lambda c: (locality.get(c[1], 0), c[0]))
            if locality.get(best[1], 0) > 0:
                return best[2]
        return (packed[-1] if packed else candidates[0])[2]

    async def _memory_monitor_loop(self):
        """OOM defense (reference: memory_monitor.h:52 + the group-by-owner
        worker killing policy): while node memory is above the threshold,
        kill the newest worker of the job with the most workers, record the
        kill so the owner can surface OutOfMemoryError, and repeat until
        back under — one worker dies, the node survives."""
        from ray_tpu._private.memory_monitor import MemoryMonitor

        monitor = MemoryMonitor()
        period = RAY_CONFIG.memory_monitor_refresh_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            try:
                pids = [w.pid for w in self.workers.values()]
                over, why = monitor.over_threshold(pids)
                if not over:
                    continue
                victim = MemoryMonitor.pick_victim([
                    {"pid": w.pid, "job": w.job_hex,
                     "started": w.last_assigned, "_w": w}
                    for w in self.workers.values()])
                if victim is None:
                    logger.warning("OOM pressure but no workers to kill: %s",
                                   why)
                    continue
                w = victim["_w"]
                logger.warning(
                    "OOM defense: killing worker pid=%d (job=%s, newest of "
                    "largest owner group) — %s", w.pid, w.job_hex, why)
                if w.address:
                    self.oom_kills[w.address] = time.monotonic()
                    if len(self.oom_kills) > 256:
                        oldest = min(self.oom_kills, key=self.oom_kills.get)
                        del self.oom_kills[oldest]
                try:
                    w.proc.kill()
                except Exception as e:
                    logger.debug("OOM kill of pid %s failed (already "
                                 "exited?): %s", w.pid, e)
            except Exception:
                logger.exception("memory monitor iteration failed")

    async def _heartbeat_loop(self):
        period = RAY_CONFIG.health_check_period_ms / 1000.0
        while True:
            try:
                reply = wire.loads(await self.gcs.call("Heartbeat", wire.dumps({
                    "node_id": self.node_id,
                    "available": dict(self.available),
                    # lease count keeps zero-resource actors visible to the
                    # autoscaler's idle detection
                    "num_leases": len(self.leases),
                    # parked lease shapes = autoscaler demand
                    "pending_shapes": [
                        {"resources": p["resources"],
                         "selector": p.get("selector", {}),
                         "waiter_id": rid}
                        for rid, p in list(self._parked.items())],
                }), timeout=5.0, retries=0))
                if reply.get("status") == "unknown_node":
                    info = NodeInfo(
                        node_id=self.node_id, address=self.server.address,
                        object_store_address=self.server.address,
                        total_resources=dict(self.total_resources),
                        labels=dict(self.labels), is_head=self.is_head)
                    await self.gcs.call("RegisterNode", wire.dumps({"info": info}))
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("heartbeat/re-register to GCS failed "
                             "(will retry): %s", e)
            await asyncio.sleep(period)

    async def _metrics_loop(self):
        """Always-on raylet runtime metrics (reference: the raylet-side
        ray_* gauges in metric_defs.cc pushed through the metrics agent):
        lease-queue depth, object-store occupancy + spill counts, worker
        pool size, event-loop lag — set here and auto-published to the GCS
        metrics namespace so the dashboard's /metrics exposes them without
        any manual publish call."""
        from ray_tpu.util.metrics import Gauge, scrape_metrics

        gauges = {
            "lease_queue": Gauge(
                "ray_tpu_raylet_lease_queue_depth",
                "granted-lease waiters parked at this raylet"),
            "parked": Gauge(
                "ray_tpu_raylet_parked_lease_shapes",
                "unplaceable lease shapes reported as autoscaler demand"),
            "leases": Gauge("ray_tpu_raylet_leases_held",
                            "currently granted worker leases"),
            "workers": Gauge("ray_tpu_raylet_workers",
                             "live worker processes on this node"),
            "store_bytes": Gauge("ray_tpu_object_store_bytes",
                                 "bytes resident in the local object store"),
            "store_objects": Gauge("ray_tpu_object_store_objects",
                                   "objects resident in the local store"),
            "spilled": Gauge("ray_tpu_object_store_spilled_objects",
                             "objects spilled to external storage (total)"),
            "restored": Gauge("ray_tpu_object_store_restored_objects",
                              "objects restored from external storage (total)"),
            "loop_lag": Gauge("ray_tpu_raylet_loop_lag_seconds",
                              "raylet event-loop scheduling delay"),
            "pool_warm": Gauge(
                "ray_tpu_worker_pool_warm",
                "registered default-env workers idle in the warm pool"),
            "pool_idle": Gauge("ray_tpu_worker_pool_idle",
                               "idle workers (any job/runtime-env)"),
            "zygote_up": Gauge("ray_tpu_worker_pool_zygote_alive",
                               "1 while the zygote fork server is serving"),
        }
        node_tag = {"node_id": self.node_id.hex()[:16]}
        for g in gauges.values():
            g.set_default_tags(node_tag)
        interval = RAY_CONFIG.metrics_flush_interval_s
        key = f"raylet_{self.node_id.hex()[:10]}"
        while True:
            before = time.monotonic()
            await asyncio.sleep(interval)
            lag = max(0.0, time.monotonic() - before - interval)
            try:
                gauges["loop_lag"].set(lag)
                gauges["lease_queue"].set(len(self._lease_waiters))
                gauges["parked"].set(len(self._parked))
                gauges["leases"].set(len(self.leases))
                gauges["workers"].set(len(self.workers))
                gauges["store_bytes"].set(self.store.used)
                gauges["store_objects"].set(len(self.store.objects))
                gauges["spilled"].set(self.store.num_spilled)
                gauges["restored"].set(self.store.num_restored)
                pool = self.provisioner.snapshot()
                gauges["pool_warm"].set(pool["warm_default_env"])
                gauges["pool_idle"].set(pool["idle_workers"])
                gauges["zygote_up"].set(1.0 if pool["zygote_alive"] else 0.0)
                payload = {"pid": os.getpid(), "time": time.time(),
                           "node": self.node_id.hex(),
                           "metrics": scrape_metrics()}
                # one batched KV round trip for both namespaces (metrics +
                # the /api/workers pool mirror)
                await self.gcs.call("KVMultiPut", wire.dumps({"items": [
                    {"ns": "metrics", "key": key,
                     "value": wire.dumps(payload)},
                    {"ns": "workers", "key": key,
                     "value": wire.dumps({
                         "node": self.node_id.hex(), "time": time.time(),
                         "pool": pool})},
                ]}), timeout=10.0, retries=0)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("raylet metrics publish failed (will retry): %s", e)
            except Exception:
                logger.exception("raylet metrics iteration failed")

    # ------------------------------------------------------------------
    # worker pool (reference: src/ray/raylet/worker_pool.h:276)
    # ------------------------------------------------------------------

    def _spawn_worker(self, renv: Optional[dict] = None,
                      renv_hash: str = "",
                      python_exe: Optional[str] = None) -> WorkerProc:
        cmd = [
            python_exe or sys.executable, "-m", "ray_tpu._private.worker_main",
            "--raylet-address", self.server.address,
            "--gcs-address", self.gcs_address,
            "--node-id", self.node_id.hex(),
            "--log-dir", self.log_dir,
        ]
        env = self._spawn_env
        if python_exe:
            # pip/uv env: the worker runs on the venv interpreter
            venv_root = os.path.dirname(os.path.dirname(python_exe))
            env = dict(env, VIRTUAL_ENV=venv_root,
                       PATH=os.path.join(venv_root, "bin") + os.pathsep
                       + env.get("PATH", os.environ.get("PATH", "")))
        if renv:
            import base64 as _b64
            import json as _json

            cmd += ["--runtime-env",
                    _b64.b64encode(_json.dumps(renv).encode()).decode()]
            if renv.get("env_vars"):
                env = dict(env, **renv["env_vars"])
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=self._log_file("worker_stdout"), stderr=subprocess.STDOUT,
        )
        w = WorkerProc(proc, renv_hash)
        self.workers[w.pid] = w
        return w

    async def _spawn_worker_async(self, renv: Optional[dict] = None,
                                  renv_hash: str = "",
                                  python_exe: Optional[str] = None
                                  ) -> WorkerProc:
        """Spawn-path router (reference: worker_pool StartWorkerProcess):
        fork from the zygote when possible — the child starts with the
        heavy stack already imported — else cold ``Popen``. pip/uv envs
        always cold-spawn (the venv has a different interpreter)."""
        if python_exe is None:
            pid = await self.provisioner.fork_worker(renv)
            if pid is not None:
                return self._register_forked(pid, renv_hash)
        self.provisioner.stats["cold_spawns"] += 1
        _pool_obs()["cold"].inc()
        return self._spawn_worker(renv, renv_hash, python_exe)

    def _register_forked(self, pid: int, renv_hash: str = "") -> WorkerProc:
        """Track a zygote-forked worker like any spawned one."""
        from ray_tpu._private.provisioner.pool import ForkedProc

        w = WorkerProc(ForkedProc(pid, self.provisioner), renv_hash)
        self.workers[w.pid] = w
        return w

    def _scan_idle(self, job_hex: Optional[str],
                   renv_hash: str = "") -> Optional[WorkerProc]:
        """Non-blocking warm-pool pop: an idle worker compatible with this
        (job, runtime-env) pair, adopted without any spawn."""
        for i, w in enumerate(self.idle_workers):
            if (w.job_hex is None or w.job_hex == job_hex) \
                    and w.renv_hash == renv_hash:
                self.idle_workers.pop(i)
                w.job_hex = w.job_hex or job_hex
                return w
        return None

    def _log_file(self, name):
        if not self.log_dir:
            return subprocess.DEVNULL
        os.makedirs(self.log_dir, exist_ok=True)
        return open(os.path.join(self.log_dir, f"{name}_{self.node_id.hex()[:8]}.log"), "ab")

    async def _pop_worker(self, job_hex: Optional[str],
                          renv: Optional[dict] = None,
                          renv_hash: str = "") -> WorkerProc:
        t0 = time.monotonic()
        while True:
            w = self._scan_idle(job_hex, renv_hash)
            if w is not None:
                self.provisioner.stats["hits"] += 1
                _pool_obs()["hits"].inc()
                _pool_obs()["adoption"].observe(time.monotonic() - t0)
                return w
            # bound concurrent spawns: each new worker pays a full
            # interpreter+import start-up; a spawn storm starves the very
            # tasks the leases are for (reference: worker_pool.h's
            # maximum_startup_concurrency)
            async with self._spawn_sem:
                w = self._scan_idle(job_hex, renv_hash)
                if w is not None:
                    self.provisioner.stats["hits"] += 1
                    _pool_obs()["hits"].inc()
                    _pool_obs()["adoption"].observe(time.monotonic() - t0)
                    return w
                self.provisioner.stats["misses"] += 1
                _pool_obs()["misses"].inc()
                python_exe = None
                if renv and "pip" in renv:
                    # venv build is blocking (pip install): off the loop.
                    # Raises RuntimeEnvSetupError to the lease path, which
                    # surfaces it to the owner as the task's error
                    # (reference: runtime-env agent failure handling)
                    from ray_tpu._private.runtime_env import ensure_env_python

                    python_exe = await asyncio.get_event_loop()\
                        .run_in_executor(None, ensure_env_python, renv)
                w = await self._spawn_worker_async(renv, renv_hash, python_exe)
                await asyncio.wait_for(w.registered,
                                       RAY_CONFIG.worker_start_timeout_s)
                w.job_hex = job_hex
                _pool_obs()["adoption"].observe(time.monotonic() - t0)
                return w

    async def _rpc_RegisterWorker(self, req, conn):
        pid = req["pid"]
        w = self.workers.get(pid)
        if w is None:
            # worker started by someone else (e.g. driver-side tests); track it
            return {"status": "unknown"}
        w.address = req["address"]
        self.workers_by_addr[w.address] = w
        w.client = RetryingRpcClient(w.address)
        if not w.registered.done():
            w.registered.set_result(True)
        return {"status": "ok", "node_id": self.node_id.hex()}

    async def _log_monitor_loop(self):
        """Tail this node's worker stdout and publish new lines to the GCS
        "logs" channel so drivers can print remote worker output
        (reference: _private/log_monitor.py:117)."""
        path = os.path.join(
            self.log_dir, f"worker_stdout_{self.node_id.hex()[:8]}.log")
        pos = 0
        node = self.node_id.hex()[:8]
        while True:
            await asyncio.sleep(0.5)
            try:
                with open(path, "rb") as f:
                    f.seek(pos)
                    data = f.read()
                    pos = f.tell()
            except FileNotFoundError:
                continue
            if not data:
                continue
            lines = data.decode(errors="replace").splitlines()
            try:
                await self.gcs.call("Publish", wire.dumps({
                    "channel": "logs",
                    "message": {"node": node, "lines": lines[:200]},
                }), timeout=5.0, retries=0)
            except Exception as e:
                logger.debug("log publish to GCS failed (%d lines "
                             "dropped): %s", len(lines), e)

    async def _prewarm_store(self):
        """Pre-touch arena pages in the background so early large puts
        don't pay first-touch fault costs (chunked; yields the loop)."""
        offset = 0
        while True:
            nxt = self.store.prewarm_step(offset)
            if nxt is None:
                return
            offset = nxt
            await asyncio.sleep(0.02)

    async def _prestart_workers(self):
        """Warm the pool so first leases don't pay interpreter start-up
        (reference: worker_pool prestart). Forks from the zygote when it is
        up; the provisioner's replenish loop keeps the pool topped up after
        grants drain it."""
        for _ in range(max(0, RAY_CONFIG.prestart_workers)):
            try:
                async with self._spawn_sem:
                    w = await self._spawn_worker_async()
                    await asyncio.wait_for(
                        w.registered, RAY_CONFIG.worker_start_timeout_s)
                w.job_hex = None
                self.idle_workers.append(w)
            except Exception as e:
                logger.debug("prestart worker spawn failed; stopping "
                             "prestart: %s", e)
                return

    async def _monitor_workers_loop(self):
        while True:
            await asyncio.sleep(0.25)
            for pid, w in list(self.workers.items()):
                code = w.proc.poll()
                if code is None:
                    continue
                self.workers.pop(pid, None)
                self.workers_by_addr.pop(w.address, None)
                if w in self.idle_workers:
                    self.idle_workers.remove(w)
                for lease_id in list(w.leases):
                    self._release_lease(lease_id)
                if w.address:
                    reason = f"exit code {code}"
                    if w.address in self.oom_kills:
                        # attribute memory-monitor kills at the mechanism
                        # level: actor owners see the OOM cause too
                        reason = ("OOM-killed by the node memory monitor "
                                  f"({reason})")
                    logger.warning("worker %s (pid %d) exited: %s",
                                   w.address, pid, reason)
                    try:
                        await self.gcs.call("WorkerDied", wire.dumps({
                            "worker_address": w.address,
                            "node_id": self.node_id.hex(),
                            "reason": reason,
                        }), retries=2)
                    except (RpcError, asyncio.TimeoutError, OSError) as e:
                        logger.debug("WorkerDied notify for %s failed: %s",
                                     w.address, e)

    # ------------------------------------------------------------------
    # leases (reference: node_manager.cc:1820 HandleRequestWorkerLease)
    # ------------------------------------------------------------------

    def _lease_pool(self, pg: Optional[bytes], bundle_index: int):
        """Resolve the resource pool a lease draws from / credits back to.

        Returns None for a PG-backed lease whose group (or bundle) is gone:
        grants must be refused (the reference fails tasks routed to removed
        groups, placement_group_resource_manager.cc), and returns must NOT
        credit the node pool — ReleasePGBundles already returned the whole
        bundle reserve, so crediting again leaks phantom capacity (+1 CPU
        per cached lease returning after group removal)."""
        if pg is None:
            return self.available
        bundles = self.pg_available.get(pg)
        if bundles is None:
            return None
        if bundle_index in bundles:
            return bundles[bundle_index]
        if bundle_index < 0 and bundles:
            return bundles[min(bundles.keys())]
        return None

    async def _rpc_RequestWorkerLease(self, req, conn):
        from ray_tpu._private.runtime_env import env_hash

        resources = req["resources"]
        pg = req.get("pg")
        bundle_index = req.get("bundle_index", -1)
        selector = req.get("label_selector") or {}
        allow_spill = bool(req.get("allow_spillback"))
        locality = req.get("locality") or {}
        renv = req.get("runtime_env")
        renv_hash = env_hash(renv)
        job_hex = req["job_id"].hex() if req.get("job_id") is not None else None
        # renv-keyed warm pool: remember the hottest non-default env so the
        # replenish loop keeps warm workers forked for it too
        self.provisioner.note_renv(renv_hash, renv)
        deadline = time.monotonic() + RAY_CONFIG.worker_start_timeout_s
        # the two-level path sends plain leases here directly: this raylet
        # must check the label selector itself (the legacy GCS PickNode
        # path pre-filters, so selector-carrying requests it routed are
        # always satisfied and the check is a no-op for them)
        local_ok = pg is not None or (
            label_match(self.labels, selector)
            and resources_ge(self.total_resources, resources))
        if not local_ok:
            if allow_spill:
                alt = self._pick_spill_node(resources, selector,
                                            require_available=False,
                                            locality=locality)
                if alt:
                    return {"status": "spillback", "retry_at": alt}
            if pg is None and label_match(self.labels, selector):
                return {"status": "infeasible",
                        "total": dict(self.total_resources)}
            return {"status": "infeasible_cluster"}
        parked_id = None
        try:
            while True:
                pool = self._lease_pool(pg, bundle_index)
                if pool is None:
                    return {"status": "pg_removed"}
                if resources_ge(pool, resources):
                    resources_sub(pool, resources)
                    try:
                        w = await self._pop_worker(job_hex, renv, renv_hash)
                    except RuntimeEnvSetupError as e:
                        # deterministic env-build failure: a structured
                        # terminal status, not a retriable RPC error —
                        # the owner fails the task with the pip output
                        resources_add(pool, resources)
                        return {"status": "runtime_env_failed",
                                "error": str(e)}
                    except (asyncio.TimeoutError, Exception):
                        resources_add(pool, resources)
                        raise
                    grant = self._record_grant(w, resources, pg, bundle_index)
                    # batched multi-grant (reference: the pipelined lease
                    # requests this amortizes in normal_task_submitter.cc):
                    # the owner asked for up to `count` leases; warm
                    # registered workers are granted instantly, then the
                    # REMAINDER is forked from the zygote (spawn-backed
                    # top-up) so the batch no longer caps at whatever
                    # happened to be registered
                    extras = []
                    want = min(int(req.get("count", 1)),
                               max(1, RAY_CONFIG.lease_max_grants))
                    while len(extras) + 1 < want:
                        xpool = self._lease_pool(pg, bundle_index)
                        if xpool is None or not resources_ge(xpool, resources):
                            break
                        w2 = self._scan_idle(job_hex, renv_hash)
                        if w2 is None:
                            break
                        resources_sub(xpool, resources)
                        self.provisioner.stats["hits"] += 1
                        _pool_obs()["hits"].inc()
                        extras.append(self._record_grant(
                            w2, resources, pg, bundle_index))
                    short = want - 1 - len(extras)
                    if short > 0 and not (renv and "pip" in renv):
                        extras.extend(await self._spawn_grant_topup(
                            short, job_hex, renv, renv_hash, resources,
                            pg, bundle_index, deadline))
                    _pool_obs()["grant_batch"].observe(1 + len(extras))
                    reply = dict(grant, status="granted",
                                 node_id=self.node_id.hex())
                    if extras:
                        reply["extra_grants"] = extras
                    return reply
                if allow_spill:
                    # busy here but a peer has capacity NOW: spill back
                    # (reference: cluster_lease_manager.cc:421)
                    alt = self._pick_spill_node(resources, selector,
                                                require_available=True)
                    if alt:
                        return {"status": "spillback", "retry_at": alt}
                if time.monotonic() > deadline:
                    return {"status": "busy"}
                if parked_id is None:
                    parked_id = uuid.uuid4().hex
                    self._parked[parked_id] = {"resources": dict(resources),
                                               "selector": dict(selector)}
                fut = asyncio.get_event_loop().create_future()
                self._lease_waiters.append(fut)
                try:
                    await asyncio.wait_for(fut, timeout=1.0)
                except asyncio.TimeoutError:
                    pass
        finally:
            if parked_id is not None:
                self._parked.pop(parked_id, None)

    async def _spawn_grant_topup(self, short: int, job_hex: Optional[str],
                                 renv: Optional[dict], renv_hash: str,
                                 resources: Dict[str, float],
                                 pg: Optional[bytes],
                                 bundle_index: int,
                                 deadline: float) -> List[dict]:
        """Fork the under-granted remainder of a multi-grant lease reply
        (grant warm now, fork the rest): a ``count=N`` request is served
        with N grants instead of capping at currently-registered workers.
        Doubles as the heterogeneous-shape fallback — a (job, runtime-env)
        shape with NO warm workers at all still receives its full batch,
        forked at the exact shape, rather than under-granting because the
        pool was warmed for a different shape. Resources are debited up
        front and credited back for forks that fail or miss the
        registration window.

        ``deadline`` is the enclosing lease request's deadline: every
        registration wait is bounded by the time remaining, so the reply
        ships before the OWNER's RPC timeout (worker_start_timeout_s + 30)
        — a reply that outlived it would trigger an owner retry and grant
        a second full batch, stranding the first batch's debited leases."""
        if not self.provisioner.zygote_alive \
                or time.monotonic() >= deadline:
            return []
        debited = 0
        for _ in range(short):
            if len(self.workers) + debited >= RAY_CONFIG.max_workers_per_node:
                break
            pool = self._lease_pool(pg, bundle_index)
            if pool is None or not resources_ge(pool, resources):
                break
            resources_sub(pool, resources)
            debited += 1
        if not debited:
            return []

        async def _one():
            try:
                budget = deadline - time.monotonic()
                if budget <= 0:
                    return None
                async with self._spawn_sem:
                    pid = await self.provisioner.fork_worker(renv)
                    if pid is None:
                        return None
                    w = self._register_forked(pid, renv_hash)
                    try:
                        await asyncio.wait_for(
                            w.registered,
                            max(0.05, deadline - time.monotonic()))
                    except asyncio.TimeoutError:
                        # kill + untrack: a late registrant would strand in
                        # self.workers without ever joining the idle pool
                        try:
                            w.proc.kill()
                        except Exception as e:
                            logger.debug("top-up reap of pid %d failed: %s",
                                         w.pid, e)
                        self.workers.pop(w.pid, None)
                        return None
                w.job_hex = job_hex
                self.provisioner.stats["misses"] += 1
                _pool_obs()["misses"].inc()
                return self._record_grant(w, resources, pg, bundle_index)
            except Exception:
                logger.warning("spawn-backed lease top-up failed",
                               exc_info=True)
                return None

        grants = [g for g in await asyncio.gather(
            *[_one() for _ in range(debited)]) if g is not None]
        for _ in range(debited - len(grants)):
            pool = self._lease_pool(pg, bundle_index)
            if pool is not None:
                resources_add(pool, resources)
        return grants

    def _record_grant(self, w: WorkerProc, resources: Dict[str, float],
                      pg: Optional[bytes], bundle_index: int) -> dict:
        """Book one lease on an acquired worker (resources already debited)
        and return its grant entry."""
        lease_id = uuid.uuid4().hex
        w.leases.add(lease_id)
        w.last_assigned = time.monotonic()
        # remember which pool to credit on release
        self.leases[lease_id] = (w, resources, wire.dumps((pg, bundle_index)))
        return {"lease_id": lease_id, "worker_address": w.address,
                "worker_pid": w.pid}

    def _release_lease(self, lease_id: str):
        entry = self.leases.pop(lease_id, None)
        if entry is None:
            return
        w, resources, pool_key = entry
        pg, bundle_index = wire.loads(pool_key)
        pool = self._lease_pool(pg, bundle_index)
        if pool is not None:
            resources_add(pool, resources)
        w.leases.discard(lease_id)
        if w.pid in self.workers and not w.leases:
            w.idle_since = time.monotonic()
            if w not in self.idle_workers:
                self.idle_workers.append(w)
        for fut in self._lease_waiters:
            if not fut.done():
                fut.set_result(True)
        self._lease_waiters = [f for f in self._lease_waiters if not f.done()]

    async def _rpc_ReturnWorkerLease(self, req, conn):
        self._release_lease(req["lease_id"])
        return {"status": "ok"}

    async def _rpc_StoreWaitAny(self, req, conn):
        """Event-driven wait leg (reference: raylet/wait_manager.h): parks
        on the store's seal events until >= num_needed of the oids are
        local (or the bounded chunk expires); one RPC replaces the owner's
        per-ref per-tick StoreContains fan-out."""
        oids = req["oids"]
        need = max(1, req.get("num_needed", 1))
        deadline = time.monotonic() + min(req.get("timeout", 10.0), 30.0)
        while True:
            present = [o for o in oids if self.store.contains(o)]
            remaining = deadline - time.monotonic()
            if len(present) >= need or remaining <= 0:
                return {"present": present}
            present_set = set(present)
            absent = [o for o in oids if o not in present_set]
            tasks = [asyncio.ensure_future(
                self.store.wait_local(o, remaining)) for o in absent]
            try:
                await asyncio.wait(tasks,
                                   return_when=asyncio.FIRST_COMPLETED,
                                   timeout=remaining)
            finally:
                for t in tasks:
                    t.cancel()

    async def _rpc_WasWorkerOOM(self, req, conn):
        # owners ask after a push failure whether the memory monitor killed
        # the worker, to surface OutOfMemoryError instead of a generic death
        return {"oom": req["worker_address"] in self.oom_kills}

    async def _rpc_KillWorker(self, req, conn):
        w = self.workers_by_addr.get(req["worker_address"])
        if w is None:
            return {"status": "not_found"}
        try:
            w.proc.kill()
        except Exception as e:
            logger.debug("KillWorker pid %s failed (already exited?): %s",
                         w.pid, e)
        return {"status": "ok"}

    async def _rpc_GetNodeStats(self, req, conn):
        agent_stats = {}
        if req.get("agent"):
            # per-node agent sample (reference: dashboard agent reporter):
            # psutil walk of every worker, off the loop
            if not hasattr(self, "_agent"):
                from ray_tpu.dashboard.agent import NodeAgent

                self._agent = NodeAgent()
            agent_stats = await asyncio.get_event_loop().run_in_executor(
                None, self._agent.collect, list(self.workers.keys()))
        return {
            "agent": agent_stats,
            "node_id": self.node_id.hex(),
            "total_resources": dict(self.total_resources),
            "available": dict(self.available),
            "num_workers": len(self.workers),
            "num_idle": len(self.idle_workers),
            "num_leases": len(self.leases),
            "worker_pool": self.provisioner.snapshot(),
            "store": self.store.stats(),
            "labels": dict(self.labels),
            "cluster_view_size": sum(
                1 for v in self.cluster_view.values() if v["alive"]),
        }

    async def _rpc_ProfileWorker(self, req, conn):
        """Route a profiling request to one of this node's workers
        (reference: dashboard ReporterService.GetTraceback / py-spy RPC)."""
        pid = req.get("pid")
        w = self.workers.get(pid)
        if w is None or not w.address:
            return {"status": "not_found",
                    "pids": sorted(self.workers.keys())}
        method = "ProfileMemory" if req.get("kind") == "memory" \
            else "ProfileStacks"
        out = wire.loads(await w.client.call(
            method, wire.dumps(req.get("args") or {}),
            timeout=float(req.get("timeout", 60.0))))
        return {"status": "ok", "pid": pid, "profile": out}

    # ------------------------------------------------------------------
    # placement group bundles (reference: placement_group_resource_manager.cc)
    # ------------------------------------------------------------------

    async def _rpc_PreparePGBundles(self, req, conn):
        pg_id = req["pg_id"]
        # idempotent per-bundle: a 2PC retry (or a reschedule that re-plans
        # surviving bundles onto this node) reserves only indices not
        # already held — never double-subtracting, never no-op'ing away a
        # genuinely new bundle of the same group
        already = self.pg_reserved.get(pg_id, {})
        bundles: Dict[int, Dict[str, float]] = {
            i: r for i, r in req["bundles"].items() if i not in already}
        if not bundles:
            return {"status": "ok"}
        need: Dict[str, float] = {}
        for res in bundles.values():
            for k, v in res.items():
                need[k] = need.get(k, 0.0) + v
        if not resources_ge(self.available, need):
            return {"status": "insufficient"}
        resources_sub(self.available, need)
        self.pg_reserved.setdefault(pg_id, {}).update(
            {i: dict(r) for i, r in bundles.items()})
        self.pg_available.setdefault(pg_id, {}).update(
            {i: dict(r) for i, r in bundles.items()})
        return {"status": "ok"}

    async def _rpc_CommitPGBundles(self, req, conn):
        self.pg_committed.add(req["pg_id"])
        return {"status": "ok"}

    async def _rpc_ReleasePGBundles(self, req, conn):
        pg_id = req["pg_id"]
        reserved = self.pg_reserved.pop(pg_id, {})
        self.pg_available.pop(pg_id, None)
        self.pg_committed.discard(pg_id)
        back: Dict[str, float] = {}
        for res in reserved.values():
            for k, v in res.items():
                back[k] = back.get(k, 0.0) + v
        resources_add(self.available, back)
        for fut in self._lease_waiters:
            if not fut.done():
                fut.set_result(True)
        return {"status": "ok"}

    # ------------------------------------------------------------------
    # object store service + pull manager
    # ------------------------------------------------------------------

    async def _rpc_StoreCreate(self, req, conn):
        return self.store.create(req["oid"], req["size"],
                                 req.get("attempt", 0),
                                 owner=req.get("owner", ""))

    async def _rpc_StoreSeal(self, req, conn):
        attempt = req.get("attempt", 0)
        if not self.store.seal(req["oid"], attempt):
            return {"status": "stale_attempt"}
        spawn(self._announce([req["oid"]], attempt), what="object announce")
        return {"status": "ok"}

    # raylint: disable=WIRE002 store wire protocol kept for out-of-tree callers: the object-plane race tests (tests/test_object_plane_race.py) drive seal/attempt fencing through this method directly
    async def _rpc_StorePutInline(self, req, conn):
        attempt = req.get("attempt", 0)
        if not self.store.put_inline(req["oid"], req["blob"], attempt,
                                     owner=req.get("owner", "")):
            return {"status": "stale_attempt"}
        spawn(self._announce([req["oid"]], attempt), what="object announce")
        return {"status": "ok"}

    async def _rpc_StoreDeleteStale(self, req, conn):
        """Directory-driven cleanup: drop our copy if it is from an older
        execution epoch than the committed one (seal-once self-healing)."""
        if self.store.object_attempt(req["oid"]) < req["attempt"]:
            self.store.delete([req["oid"]])
            return {"deleted": True}
        return {"deleted": False}

    async def _announce(self, oids: List[bytes], attempt: int = 0):
        try:
            await self.gcs.call("ObjectLocAdd", wire.dumps(
                {"oids": oids, "node_id": self.node_id,
                 "sizes": {o: self.store.object_size(o) for o in oids},
                 "attempt": attempt}), retries=2)
        except (RpcError, asyncio.TimeoutError, OSError):
            logger.warning("failed to announce %d object locations", len(oids))
        # owner-resident directory (reference:
        # ownership_object_directory.cc): the owner serves location READS
        # for its objects, so pulls stop hammering the GCS; the GCS copy
        # above remains the durable fallback. One batched RPC per owner,
        # mirroring the batched GCS announce.
        by_owner: Dict[str, list] = {}
        for o in oids:
            owner = self.store.object_owner(o)
            if owner:
                by_owner.setdefault(owner, []).append(o)
        for owner, group in by_owner.items():
            spawn(self._notify_owner(owner, "ObjectLocAnnounce", {
                "oids": group, "node_id": self.node_id.hex(),
                "address": self.server.address,
                "sizes": {o: self.store.object_size(o) or 0 for o in group},
                "attempt": attempt}))

    async def _notify_owner(self, owner: str, method: str, msg: dict):
        try:
            await self._owner_client(owner).call(
                method, wire.dumps(msg), timeout=10.0, retries=1)
        except (RpcError, asyncio.TimeoutError, OSError) as e:
            # best-effort: the GCS directory still has it
            logger.debug("%s notify to owner %s failed: %s", method, owner, e)

    def _owner_client(self, addr: str) -> RetryingRpcClient:
        from collections import OrderedDict

        cache = getattr(self, "_owner_clients", None)
        if cache is None:
            cache = self._owner_clients = OrderedDict()
        client = cache.get(addr)
        if client is None:
            if len(cache) > 128:
                _, evicted = cache.popitem(last=False)  # LRU, not newest
                # grace before close: a concurrent notify/query may still
                # be awaiting on this client
                asyncio.get_event_loop().call_later(
                    30.0, lambda c=evicted: spawn(c.close(),
                                                  what="evicted-client close"))
            client = cache[addr] = RetryingRpcClient(addr)
        else:
            cache.move_to_end(addr)
        return client

    async def _rpc_StoreGet(self, req, conn):
        oid = req["oid"]
        timeout = req.get("timeout", RAY_CONFIG.object_pull_timeout_s)
        pulling = not self.store.contains(oid) and req.get("pull", True)
        if pulling:
            # priority class rides the request: 0 = blocked get, 1 = task
            # arg, 2 = background (reference: pull_manager.cc priorities)
            self._ensure_pull(oid, prio=int(req.get("prio", 1)),
                              owner=req.get("owner", ""))
            self._pull_queue.add_waiter(oid)
        try:
            ok = await self.store.wait_local(oid, timeout)
        finally:
            if pulling:
                self._pull_queue.remove_waiter(oid)
        if not ok:
            return {"status": "timeout"}
        return self.store.access(oid)

    # raylint: disable=WIRE002 store wire protocol kept for out-of-tree callers: the object-plane race tests probe spill/eviction state through this method directly
    async def _rpc_StoreContains(self, req, conn):
        return {"contains": self.store.contains(req["oid"])}

    async def _rpc_StoreMeta(self, req, conn):
        size = self.store.object_size(req["oid"])
        return {"size": size, "attempt": self.store.object_attempt(req["oid"]),
                "owner": self.store.object_owner(req["oid"])}

    async def _rpc_StoreFetchChunk(self, req, conn):
        data = self.store.read_chunk(req["oid"], req["offset"], req["length"],
                                     req.get("attempt"))
        return {"data": data}

    async def _rpc_StoreDelete(self, req, conn):
        owners = {o: self.store.object_owner(o) for o in req["oids"]}
        self.store.delete(req["oids"])
        try:
            await self.gcs.call("ObjectLocRemove", wire.dumps(
                {"oids": req["oids"], "node_id": self.node_id}), retries=1)
        except (RpcError, asyncio.TimeoutError, OSError) as e:
            logger.debug("ObjectLocRemove(%d oids) to GCS failed: %s",
                         len(req["oids"]), e)
        for o, owner in owners.items():
            if owner:  # keep the owner-resident view from going stale
                spawn(self._notify_owner(
                    owner, "ObjectLocDrop",
                    {"oid": o, "node_id": self.node_id.hex()}))
        return {"status": "ok"}

    async def _rpc_StoreStats(self, req, conn):
        return self.store.stats()

    def _ensure_pull(self, oid: bytes, prio: int = 1, owner: str = ""):
        self._pull_queue.request(oid, prio)  # registers or upgrades
        if oid in self._pulls and not self._pulls[oid].done():
            return
        self._pulls[oid] = asyncio.ensure_future(self._pull(oid, prio, owner))

    async def _pull(self, oid: bytes, prio: int = 1, owner: str = ""):
        """Chunked transfer from a remote node's store (reference:
        object_manager/pull_manager.cc + push_manager.cc). Bounded
        concurrency (FIFO through a semaphore) keeps a burst of pulls from
        monopolizing the loop and network, and the SOURCE is chosen at
        random among announced holders: since every completed pull
        announces a new location, an N-node broadcast forms an organic
        fan-out tree off the origin instead of an N-deep queue on it
        (reference: the 1 GiB / 50-node broadcast envelope)."""
        await self._pull_inner(oid, prio, owner)

    async def _pull_inner(self, oid: bytes, prio: int = 1, owner: str = ""):
        import random as _random

        deadline = time.monotonic() + RAY_CONFIG.object_pull_timeout_s
        chunk = RAY_CONFIG.object_chunk_bytes
        while time.monotonic() < deadline:
            if self.store.contains(oid):
                return
            reply = None
            if owner and owner != "gcs-only":
                # owner-resident directory read; an unreachable or empty
                # owner drops us to the GCS copy for the rest of this pull
                try:
                    reply = wire.loads(await self._owner_client(owner).call(
                        "ObjectLocQuery", wire.dumps({"oid": oid}),
                        timeout=10.0, retries=1))
                    if not reply.get("locations"):
                        reply = None
                        owner = "gcs-only"
                except (RpcError, asyncio.TimeoutError, OSError):
                    reply = None
                    owner = "gcs-only"
            if reply is None:
                try:
                    reply = wire.loads(await self.gcs.call(
                        "ObjectLocGet", wire.dumps({"oid": oid}), retries=2))
                except (RpcError, asyncio.TimeoutError, OSError):
                    await asyncio.sleep(0.2)
                    continue
            locations = [l for l in reply["locations"] if l["node_id"] != self.node_id.hex()]
            if not locations:
                # nothing usable this round (possibly a stale owner view
                # listing only us): consult the GCS copy from here on
                owner = "gcs-only"
                await asyncio.sleep(0.1)
                continue
            locations[0] = _random.choice(locations)
            src = RetryingRpcClient(locations[0]["address"])
            attempt = None  # set once meta arrives; guards the except path
            try:
                # the admission bound covers only the actual TRANSFER:
                # a slot must not be parked on location polling for an
                # object nobody has announced yet. Admission is by
                # (priority class, FIFO); False means the queued pull went
                # obsolete (every waiter left) and was cancelled
                if not await self._pull_queue.admit(oid):
                    logger.info("pull %s cancelled (no waiters)",
                                oid.hex()[:12])
                    return
                try:
                    if self.store.contains(oid):
                        return
                    await self._pull_transfer(oid, src, chunk)
                finally:
                    self._pull_queue.release(oid)
                return
            except _PullRetry:
                self._pull_queue.request(oid, prio)
                await asyncio.sleep(0.1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.warning("pull %s from %s failed: %s", oid.hex()[:12],
                               locations[0]["address"], e)
                # the copy the owner pointed us at is gone/unreachable;
                # the GCS may know a live secondary — stop re-asking the
                # owner for this pull
                owner = "gcs-only"
                self._pull_queue.request(oid, prio)
                await asyncio.sleep(0.2)
            finally:
                await src.close()
        logger.warning("pull %s timed out", oid.hex()[:12])

    async def _pull_transfer(self, oid: bytes, src, chunk: int):
        meta = wire.loads(await src.call("StoreMeta", wire.dumps({"oid": oid})))
        size = meta.get("size")
        if size is None:
            raise _PullRetry()
        attempt = meta.get("attempt", 0)
        # carry the owner onto the pulled copy: this node's seal announce
        # then reaches the owner too, so secondary replicas join the
        # owner-resident directory and broadcast trees fan out there as well
        created = self.store.create(oid, size, attempt,
                                    owner=meta.get("owner", ""))
        if created["status"] in ("exists", "stale_attempt"):
            return
        if created["status"] != "ok":
            logger.warning("pull %s: local store oom", oid.hex()[:12])
            return
        try:
            offset = 0
            while offset < size:
                n = min(chunk, size - offset)
                r = wire.loads(await src.call("StoreFetchChunk", wire.dumps(
                    {"oid": oid, "offset": offset, "length": n,
                     "attempt": attempt})))
                data = r.get("data")
                if data is None:
                    raise RpcError("source evicted or displaced object mid-pull")
                try:
                    self.store.write_chunk(oid, offset, data, attempt)
                except KeyError:
                    # displaced locally by a newer attempt: clean abort —
                    # the newer copy is (or will be) the committed one
                    return
                offset += n
            if self.store.seal(oid, attempt):
                await self._announce([oid], attempt)
        except (RpcError, asyncio.TimeoutError, OSError):
            # only clean up OUR partial copy — a newer attempt may have
            # displaced the entry mid-transfer and must not be deleted
            if self.store.object_attempt(oid) == attempt \
                    and not self.store.contains(oid):
                self.store.delete([oid])
            raise

    # ------------------------------------------------------------------

    async def _handle(self, method: str, payload: bytes, conn) -> bytes:
        fn = getattr(self, f"_rpc_{method}", None)
        if fn is None:
            raise RpcError(f"raylet: unknown method {method}")
        req = wire.loads(payload) if payload else {}
        resp = await fn(req, conn)
        return wire.dumps(resp)


def main():
    from ray_tpu._private.common import die_with_parent

    die_with_parent()

    import argparse
    import json

    from ray_tpu._private.logs import setup_process_logging

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--head", action="store_true")
    parser.add_argument("--node-id", default="")
    parser.add_argument("--log-dir", default="")
    parser.add_argument("--address-file", default="")
    parser.add_argument("--object-store-memory", type=int, default=0)
    args = parser.parse_args()
    setup_process_logging("raylet", args.log_dir)

    from ray_tpu._private.object_store import sweep_stale_shm

    # sweep BEFORE the store arena is created, then construct the raylet in
    # sync context, before the event loop exists: ObjectStoreServer may
    # compile the native store (a g++ subprocess with a 120 s budget) and the
    # loop must never be parked behind it (ASY004). asyncio primitives
    # created in __init__ are loop-lazy on py>=3.10.
    swept = sweep_stale_shm()
    if swept:
        logger.info("swept %d stale shm segments", swept)
    raylet = Raylet(
        gcs_address=args.gcs_address,
        node_id=NodeID.from_hex(args.node_id) if args.node_id else None,
        resources=json.loads(args.resources),
        labels=json.loads(args.labels),
        is_head=args.head,
        port=args.port,
        log_dir=args.log_dir,
        object_store_memory=args.object_store_memory or None,
    )

    async def run():
        addr = await raylet.start()
        if args.address_file:
            tmp = args.address_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(addr)
            os.replace(tmp, args.address_file)
        # graceful stop on SIGTERM/SIGINT so the store's shm arena and
        # per-object segments are unlinked (kill -9 leftovers are reclaimed
        # by sweep_stale_shm at the next node start)
        stop_ev = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop_ev.set)
        await stop_ev.wait()
        await raylet.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()

"""Binary identifiers for jobs, tasks, actors, objects, nodes, placement groups.

Equivalent of the reference's ``src/ray/common/id.h`` /
``src/ray/design_docs/id_specification.md``: fixed-width random ids with
structured derivation (an ObjectID embeds the id of the task that produces it
plus a return index, so ownership and lineage can be recovered from the id
itself).
"""

from __future__ import annotations

import os
import struct


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes",)

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = id_bytes

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]})"


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, i: int):
        return cls(struct.pack(">I", i))


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE:])


class TaskID(BaseID):
    SIZE = 14

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JobID.SIZE:])


class ObjectID(BaseID):
    # task id (14) + big-endian return index (2)
    SIZE = 16

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int):
        return cls(task_id.binary() + struct.pack(">H", index))

    @classmethod
    def from_put(cls, task_id: TaskID, put_index: int):
        # puts use the high bit of the index space to avoid colliding with returns
        return cls(task_id.binary() + struct.pack(">H", 0x8000 | put_index))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def return_index(self) -> int:
        return struct.unpack(">H", self._bytes[TaskID.SIZE:])[0]


class PlacementGroupID(BaseID):
    SIZE = 12

"""Worker process entrypoint, spawned by the raylet's worker pool.

Reference: python/ray/_private/workers/default_worker.py — connects the
embedded CoreWorker to its node's raylet + the GCS, registers, then serves
PushTask until killed.
"""

from __future__ import annotations

import argparse

from ray_tpu._private import wire
import signal
import threading
import time


def main():
    from ray_tpu._private.common import die_with_parent

    die_with_parent()

    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-address", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--log-dir", default="")
    parser.add_argument("--runtime-env", default="",
                        help="base64 JSON runtime-env descriptor")
    args = parser.parse_args()

    from ray_tpu._private.logs import setup_process_logging

    setup_process_logging("worker", args.log_dir)
    import faulthandler

    # `kill -USR1 <pid>` dumps all thread stacks to the worker log — the
    # ray-stack equivalent for debugging silent hangs
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.core_worker import CoreWorker
    from ray_tpu._private.ids import NodeID

    core = CoreWorker(
        gcs_address=args.gcs_address,
        raylet_address=args.raylet_address,
        node_id=NodeID.from_hex(args.node_id),
        is_driver=False,
    )
    core.current_task_id = None
    core.current_actor_id = None
    core.connect()
    worker_mod._global_worker = core

    if args.runtime_env:
        import base64
        import json

        from ray_tpu._private import runtime_env as renv_mod

        renv = json.loads(base64.b64decode(args.runtime_env))

        def kv_get(key: str):
            return core._run(core._gcs_call(
                "KVGet", {"ns": "renv", "key": key}))["value"]

        renv_mod.apply(renv, kv_get)

    import os

    core._run(core.raylet.call("RegisterWorker", wire.dumps({
        "pid": os.getpid(), "address": core.address})))

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    while not stop.is_set():
        time.sleep(1.0)


if __name__ == "__main__":
    main()

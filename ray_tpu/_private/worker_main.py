"""Worker process entrypoint, spawned by the raylet's worker pool.

Reference: python/ray/_private/workers/default_worker.py — connects the
embedded CoreWorker to its node's raylet + the GCS, registers, then serves
PushTask until killed.

Two spawn paths share ``run_worker``:

- cold: the raylet ``Popen``s ``python -m ray_tpu._private.worker_main``
  (fresh interpreter, pays the full import cost) — ``main()`` below;
- warm: the provisioner's zygote (``_private/provisioner/zygote.py``) forks
  a child that calls ``run_worker`` directly — imports are already resident,
  so start-up is fork(2) + connect.
"""

from __future__ import annotations

import argparse

from ray_tpu._private import wire
import os
import signal
import threading
import time
from typing import Optional


def reset_observability_after_fork() -> None:
    """Reset every inherited observability buffer in a forked worker.

    The zygote image carries live span buffers, task-event buffers and a
    metrics registry; a forked child that keeps them re-emits the parent
    process's buffered events/spans under its own identity and re-reports
    the parent's accumulated counters (the ``_obs_proc_tag`` class of
    fork bug, PR 8). Called by the zygote's fork child before
    :func:`run_worker`; safe to call in any process."""
    from ray_tpu._private import task_events
    from ray_tpu.util import goodput, metrics, tracing

    task_events.reset_after_fork()
    tracing.reset_after_fork()
    metrics.reset_after_fork()
    goodput.reset_after_fork()


def run_worker(raylet_address: str, gcs_address: str, node_id_hex: str,
               log_dir: str = "", runtime_env: Optional[dict] = None,
               orphan_ppid: Optional[int] = None) -> None:
    """Boot the worker runtime and serve until SIGTERM (or orphaning).

    ``orphan_ppid``: zygote-forked workers cannot use PDEATHSIG against the
    raylet (their parent is the zygote, and inheriting the zygote's PDEATHSIG
    would kill every worker on a zygote crash) — instead they watch for
    reparenting (zygote gone). A zygote crash alone is SURVIVABLE (the
    provisioner respawns it and this worker keeps its leases), so on
    orphaning the worker exits only once the raylet itself stops answering
    — the actual dead-cluster signal.
    """
    from ray_tpu._private.logs import setup_process_logging

    setup_process_logging("worker", log_dir)
    import faulthandler

    # `kill -USR1 <pid>` dumps all thread stacks to the worker log — the
    # ray-stack equivalent for debugging silent hangs
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.core_worker import CoreWorker
    from ray_tpu._private.ids import NodeID

    core = CoreWorker(
        gcs_address=gcs_address,
        raylet_address=raylet_address,
        node_id=NodeID.from_hex(node_id_hex),
        is_driver=False,
    )
    core.current_task_id = None
    core.current_actor_id = None
    core.connect()
    worker_mod._global_worker = core

    if runtime_env:
        from ray_tpu._private import runtime_env as renv_mod

        def kv_get(key: str):
            return core._run(core._gcs_call(
                "KVGet", {"ns": "renv", "key": key}))["value"]

        renv_mod.apply(runtime_env, kv_get)

    core._run(core.raylet.call("RegisterWorker", wire.dumps({
        "pid": os.getpid(), "address": core.address})))

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    last_probe = 0.0
    while not stop.is_set():
        if orphan_ppid is not None and os.getppid() != orphan_ppid \
                and time.monotonic() - last_probe > 5.0:
            # reparented: the zygote died. If the raylet still answers this
            # is a survivable zygote crash (it gets respawned); only a dead
            # raylet means the cluster is gone and lingering would orphan us
            last_probe = time.monotonic()
            try:
                core._run(core.raylet.call(
                    "StoreStats", b"", timeout=5.0, retries=1), 15.0)
            except Exception as e:
                import logging

                logging.getLogger("ray_tpu.worker").warning(
                    "orphaned (zygote gone) and raylet unreachable (%s); "
                    "exiting", e)
                break
        time.sleep(1.0)


def main():
    from ray_tpu._private.common import die_with_parent

    die_with_parent()

    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-address", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--log-dir", default="")
    parser.add_argument("--runtime-env", default="",
                        help="base64 JSON runtime-env descriptor")
    args = parser.parse_args()

    renv = None
    if args.runtime_env:
        import base64
        import json

        renv = json.loads(base64.b64decode(args.runtime_env))

    run_worker(args.raylet_address, args.gcs_address, args.node_id,
               log_dir=args.log_dir, runtime_env=renv)


if __name__ == "__main__":
    main()

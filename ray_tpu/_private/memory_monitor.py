"""Node memory monitor + worker-killing policy (OOM defense).

Reference: ``src/ray/common/memory_monitor.h:52`` — a periodic check of node
memory usage against a threshold — and
``src/ray/raylet/worker_killing_policy_group_by_owner.cc`` — when over the
threshold, workers are grouped by owning job and the NEWEST worker of the
LARGEST group is killed first (preserves older, likely-further-along work
and spreads pain across jobs fairly).

Two accounting modes:
- system (default): usage = 1 - MemAvailable/MemTotal from /proc/meminfo —
  what the reference does on a dedicated node;
- budget (``memory_monitor_capacity_bytes`` > 0): usage = sum of tracked
  worker RSS / capacity — deterministic on shared CI hosts where system
  memory is dominated by other tenants.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import RAY_CONFIG

_PAGE = os.sysconf("SC_PAGE_SIZE")


def worker_rss(pid: int) -> int:
    """Resident set size of one process in bytes (0 if gone)."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


def system_usage() -> Tuple[int, int]:
    """(used, total) bytes from /proc/meminfo (available-based, like the
    reference's MemoryMonitor)."""
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total and avail:
                    break
    except OSError:
        return 0, 0
    return max(0, total - avail), total


class MemoryMonitor:
    """Threshold check + group-by-owner victim selection."""

    def __init__(self, threshold: Optional[float] = None,
                 capacity_bytes: Optional[int] = None):
        self.threshold = (threshold if threshold is not None
                          else RAY_CONFIG.memory_usage_threshold)
        self.capacity = (capacity_bytes if capacity_bytes is not None
                         else RAY_CONFIG.memory_monitor_capacity_bytes)

    def usage(self, worker_pids: List[int]) -> Tuple[float, int, int]:
        """(fraction, used, cap) under the configured accounting mode."""
        if self.capacity > 0:
            used = sum(worker_rss(pid) for pid in worker_pids)
            return used / self.capacity, used, self.capacity
        used, total = system_usage()
        if total <= 0:
            return 0.0, 0, 0
        return used / total, used, total

    def over_threshold(self, worker_pids: List[int]) -> Tuple[bool, str]:
        frac, used, cap = self.usage(worker_pids)
        if frac <= self.threshold:
            return False, ""
        return True, (f"memory usage {frac:.0%} ({used >> 20} MiB of "
                      f"{cap >> 20} MiB) above threshold {self.threshold:.0%}")

    @staticmethod
    def pick_victim(workers: List[dict]) -> Optional[dict]:
        """Group-by-owner newest-first: workers are dicts with at least
        {"pid", "job", "started"}, where "started" is the LAST WORK
        ASSIGNMENT time (not process age — reused workers are old
        processes that may hold the newest work); returns the victim dict
        or None. (reference: worker_killing_policy_group_by_owner.cc ranks
        by task assignment recency)"""
        if not workers:
            return None
        groups: Dict[str, List[dict]] = {}
        for w in workers:
            groups.setdefault(w.get("job") or "?", []).append(w)
        # largest group first; tie-break on the group with the newest worker
        group = max(groups.values(),
                    key=lambda g: (len(g), max(w["started"] for w in g)))
        return max(group, key=lambda w: w["started"])  # newest in the group

"""GCS persistence: pluggable table store with an in-memory and a durable
file-backed flavor.

Role-equivalent of the reference's ``StoreClient`` abstraction
(``src/ray/gcs/store_client/``: ``InMemoryStoreClient``,
``RedisStoreClient``) that backs GCS fault tolerance — on restart the GCS
reloads all tables (``gcs_init_data.cc``) and resumes. The environment has
no Redis, so the durable flavor is an append-only journal with snapshot
compaction on open (same recovery semantics: replay-in-order, last write
wins).

Record format (journal): 4-byte big-endian length + wire-msgpack
``[op, table, key, value]`` record (typed schema, wire.py), fsync'd per batch. Corrupt/short tails
(crash mid-write) are truncated on load.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Iterable, Optional, Tuple

from ray_tpu._private import wire

logger = logging.getLogger("ray_tpu.store")

_PUT, _DEL, _DEL_TABLE = 0, 1, 2


class StoreClient:
    """Synchronous table/key/value store. Values are opaque bytes."""

    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def delete_table(self, table: str) -> None:
        raise NotImplementedError

    def all(self, table: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    def __init__(self):
        self._tables: Dict[str, Dict[str, bytes]] = {}

    def put(self, table, key, value):
        self._tables.setdefault(table, {})[key] = value

    def get(self, table, key):
        return self._tables.get(table, {}).get(key)

    def delete(self, table, key):
        self._tables.get(table, {}).pop(key, None)

    def delete_table(self, table):
        self._tables.pop(table, None)

    def all(self, table):
        return dict(self._tables.get(table, {}))


class FileStoreClient(StoreClient):
    """Append-only journal + snapshot compaction, crash-safe enough for the
    GCS restart path (tail truncation on partial writes)."""

    SNAPSHOT = "snapshot.db"
    JOURNAL = "journal.db"
    # first bytes of every journal; a journal without it (older/other
    # format) is preserved as .incompat and reported, never silently
    # truncated to nothing
    MAGIC = b"RTPUJ1\n"
    # compact when the journal holds this many records beyond the snapshot
    COMPACT_EVERY = 50_000

    # coalesce fsyncs: at most one per this interval (bounded-loss window —
    # the GCS state is also rebuilt from raylet heartbeats, so a few ms of
    # recent mutations is an acceptable crash window vs. stalling the
    # control-plane event loop on every record)
    FSYNC_INTERVAL_S = 0.01

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._tables: Dict[str, Dict[str, bytes]] = {}
        self._journal_records = 0
        self._last_fsync = 0.0
        self._load()
        jpath = os.path.join(self.dir, self.JOURNAL)
        fresh = not os.path.exists(jpath) or os.path.getsize(jpath) == 0
        self._journal = open(jpath, "ab")
        if fresh:
            self._journal.write(self.MAGIC)
            self._journal.flush()

    # -- recovery ------------------------------------------------------

    def _load(self):
        snap = os.path.join(self.dir, self.SNAPSHOT)
        if os.path.exists(snap):
            try:
                with open(snap, "rb") as f:
                    self._tables = wire.loads(f.read())
            except Exception:
                corrupt = snap + ".corrupt"
                logger.error(
                    "GCS snapshot %s is unreadable — starting from the journal "
                    "alone; most persisted state is LOST. Saved the bad file "
                    "as %s", snap, corrupt, exc_info=True)
                try:
                    os.replace(snap, corrupt)
                except OSError as e:
                    logger.debug("quarantine rename of %s failed: %s", snap, e)
                self._tables = {}
        for op, table, key, value in self._read_journal():
            self._apply(op, table, key, value)
            self._journal_records += 1

    def _read_journal(self) -> Iterable[Tuple[int, str, str, Optional[bytes]]]:
        path = os.path.join(self.dir, self.JOURNAL)
        if not os.path.exists(path):
            return
        good = 0
        with open(path, "rb") as f:
            head = f.read(len(self.MAGIC))
            if head != self.MAGIC:
                if head:  # non-empty journal in an unknown/older format
                    incompat = path + ".incompat"
                    logger.error(
                        "GCS journal %s lacks the %r header (older or "
                        "foreign format) — refusing to replay or truncate "
                        "it; saved as %s. Durable state from that journal "
                        "is NOT loaded.", path, self.MAGIC, incompat)
                    try:
                        os.replace(path, incompat)
                    except OSError as e:
                        logger.debug("quarantine rename of %s failed: %s",
                                     path, e)
                return
            good = f.tell()
            while True:
                header = f.read(4)
                if len(header) < 4:
                    break
                length = int.from_bytes(header, "big")
                body = f.read(length)
                if len(body) < length:
                    break
                try:
                    yield wire.loads(body)
                except Exception as e:
                    logger.debug("journal replay stopped at torn/corrupt "
                                 "record (offset %d): %s", good, e)
                    break
                good = f.tell()
        size = os.path.getsize(path)
        if good < size:  # torn tail from a crash mid-append
            with open(path, "r+b") as f:
                f.truncate(good)

    def _apply(self, op, table, key, value):
        if op == _PUT:
            self._tables.setdefault(table, {})[key] = value
        elif op == _DEL:
            self._tables.get(table, {}).pop(key, None)
        elif op == _DEL_TABLE:
            self._tables.pop(table, None)

    # -- journal -------------------------------------------------------

    def _append(self, op, table, key, value):
        body = wire.dumps([op, table, key, value])
        self._journal.write(len(body).to_bytes(4, "big") + body)
        self._journal.flush()
        now = time.monotonic()
        if now - self._last_fsync >= self.FSYNC_INTERVAL_S:
            os.fsync(self._journal.fileno())
            self._last_fsync = now
        # raylint: disable=RCE001 the other write site (_load) runs once inside __init__ before the server accepts connections — construction happens-before every locked _append
        self._journal_records += 1
        if self._journal_records >= self.COMPACT_EVERY:
            self._compact_locked()

    def _compact_locked(self):
        snap = os.path.join(self.dir, self.SNAPSHOT)
        tmp = snap + ".tmp"
        with open(tmp, "wb") as f:
            f.write(wire.dumps(self._tables))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, snap)
        self._journal.close()
        self._journal = open(os.path.join(self.dir, self.JOURNAL), "wb")
        self._journal.write(self.MAGIC)
        self._journal.flush()
        self._journal_records = 0

    # -- StoreClient ---------------------------------------------------

    def put(self, table, key, value):
        with self._lock:
            self._apply(_PUT, table, key, value)
            self._append(_PUT, table, key, value)

    def get(self, table, key):
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table, key):
        with self._lock:
            self._apply(_DEL, table, key, None)
            self._append(_DEL, table, key, None)

    def delete_table(self, table):
        with self._lock:
            self._apply(_DEL_TABLE, table, "", None)
            self._append(_DEL_TABLE, table, "", None)

    def all(self, table):
        with self._lock:
            return dict(self._tables.get(table, {}))

    def close(self):
        with self._lock:
            try:
                self._journal.flush()
                os.fsync(self._journal.fileno())
                self._journal.close()
            except Exception as e:
                logger.debug("journal close failed: %s", e)


def make_store(persist_dir: str = "") -> StoreClient:
    return FileStoreClient(persist_dir) if persist_dir else InMemoryStoreClient()

"""Typed, versioned wire schema for control-plane RPC payloads.

Reference: ``src/ray/protobuf/{common,gcs_service,node_manager}.proto`` — the
reference gives every control-plane message a typed, versioned schema; a
pickle-speaking control port is arbitrary-code-execution for anyone who can
reach it, and has zero cross-version compatibility. Here every control-plane
payload is strict msgpack: only primitives, containers, and an explicit
registry of framework structs (encoded as msgpack ext types with per-class
field lists) can cross the wire.

Security property: :func:`loads` never executes user-controlled code. Decoding
rehydrates only classes in the fixed registry below, by constructing them from
plain field values. A pickled blob fed to :func:`loads` raises — it is never
unpickled. User payloads (task args, results, exceptions, function blobs)
remain opaque ``bytes`` fields inside these typed envelopes and are
deserialized only in user-trust context (the owning driver or the executing
worker), exactly like the reference keeps user data inside ``bytes`` protobuf
fields.

Versioning: :data:`WIRE_VERSION` rides in every RPC frame header (rpc.py);
frames with a missing or mismatched version are rejected before the payload is
touched. Struct fields are encoded by NAME, so adding a field with a default
is forward- and backward-compatible within a version; renames/removals bump
``WIRE_VERSION``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple, Type

import msgpack

WIRE_VERSION = 1

_EXT_STRUCT = 1  # registered framework struct: packb([tag, {field: value}])
_EXT_ID = 2  # framework id: packb([tag, binary])
_EXT_SET = 3  # set: packb([items])
_EXT_NDARRAY = 5  # numpy array: packb([dtype_str, shape, raw_bytes])


class WireError(TypeError):
    """A value outside the typed schema tried to cross the control plane."""


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

# raylint: disable=FRK001 import-time append-only registry, identical in parent and child: register_* runs at module import, so the zygote image and a fresh process hold the same entries and a reset would only re-register them
_STRUCTS: Dict[str, tuple] = {}  # tag -> (cls, fields, decode)
_STRUCT_TAGS: Dict[Type, str] = {}
_IDS: Dict[str, Type] = {}
_ID_TAGS: Dict[Type, str] = {}


def register_struct(cls: Type, fields: Tuple[str, ...] = None, tag: str = None,
                    decode: Callable[[dict], Any] = None) -> Type:
    """Allow ``cls`` on the wire, encoded as its named fields.

    Decoding calls ``cls(**fields)`` for dataclass-style types — missing
    fields (older sender) fall back to constructor defaults; unknown fields
    (newer sender) are dropped. Pass ``decode`` when the constructor's
    parameter names differ from the attribute names.
    """
    if fields is None:
        import dataclasses

        fields = tuple(f.name for f in dataclasses.fields(cls))
    tag = tag or cls.__name__
    if tag in _STRUCTS and _STRUCTS[tag][0] is not cls:
        raise ValueError(f"wire tag collision: {tag}")
    _STRUCTS[tag] = (cls, fields, decode)
    _STRUCT_TAGS[cls] = tag
    return cls


def register_id(cls: Type, tag: str = None) -> Type:
    tag = tag or cls.__name__
    _IDS[tag] = cls
    _ID_TAGS[cls] = tag
    return cls


def _register_builtin_types():
    from ray_tpu._private import common, ids

    for c in (ids.JobID, ids.NodeID, ids.WorkerID, ids.ActorID, ids.TaskID,
              ids.ObjectID, ids.PlacementGroupID):
        register_id(c)
    for c in (common.NodeInfo, common.TaskOptions, common.ActorOptions,
              common.TaskSpec, common.Bundle, common.PlacementGroupSpec,
              common.WorkerLease):
        register_struct(c)
    from ray_tpu.util import scheduling_strategies as ss

    for c in (ss.PlacementGroupSchedulingStrategy, ss.NodeAffinitySchedulingStrategy,
              ss.NodeLabelSchedulingStrategy, ss.SpreadSchedulingStrategy):
        register_struct(c)
    from ray_tpu.util.placement_group import PlacementGroup

    register_struct(
        PlacementGroup, fields=("id", "bundle_specs"),
        decode=lambda f: PlacementGroup(f["id"], f["bundle_specs"]))

    # weight plane (ray_tpu/weights/): mesh geometry + transfer-plan edges
    # cross the control plane (store manifests, dashboard stats)
    from ray_tpu.weights.plan import TransferEdge
    from ray_tpu.weights.spec import MeshSpec

    register_struct(
        MeshSpec, fields=("shape", "axis_names", "hosts"),
        decode=lambda f: MeshSpec(tuple(f["shape"]), tuple(f["axis_names"]),
                                  tuple(f["hosts"])))
    register_struct(
        TransferEdge,
        fields=("leaf", "src_host", "dst_host", "box", "src_box", "dst_box",
                "nbytes", "local"),
        decode=lambda f: TransferEdge(
            leaf=f["leaf"], src_host=f["src_host"], dst_host=f["dst_host"],
            box=tuple(tuple(p) for p in f["box"]),
            src_box=tuple(tuple(p) for p in f["src_box"]),
            dst_box=tuple(tuple(p) for p in f["dst_box"]),
            nbytes=f["nbytes"], local=f["local"]))


# ---------------------------------------------------------------------------
# Codec
# ---------------------------------------------------------------------------


def _default(obj: Any):
    cls = type(obj)
    tag = _ID_TAGS.get(cls)
    if tag is not None:
        return msgpack.ExtType(
            _EXT_ID, msgpack.packb([tag, obj.binary()], use_bin_type=True))
    tag = _STRUCT_TAGS.get(cls)
    if tag is not None:
        _, fields, _ = _STRUCTS[tag]
        payload = {name: getattr(obj, name) for name in fields}
        return msgpack.ExtType(
            _EXT_STRUCT,
            msgpack.packb([tag, payload], use_bin_type=True, default=_default))
    if cls is set or cls is frozenset:
        return msgpack.ExtType(
            _EXT_SET,
            msgpack.packb(sorted(obj, key=repr), use_bin_type=True, default=_default))
    import numpy as np

    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        if arr.dtype.hasobject:
            raise WireError("object-dtype arrays cannot cross the control plane")
        return msgpack.ExtType(
            _EXT_NDARRAY,
            msgpack.packb([arr.dtype.str, list(arr.shape), arr.tobytes()],
                          use_bin_type=True))
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    raise WireError(
        f"{cls.__module__}.{cls.__name__} is not wire-typed; control-plane "
        f"messages may only carry primitives, containers, and registered "
        f"framework structs (register_struct/register_id)")


def _ext_hook(code: int, data: bytes):
    if code == _EXT_ID:
        tag, binary = msgpack.unpackb(data, raw=False)
        cls = _IDS.get(tag)
        if cls is None:
            raise WireError(f"unknown wire id tag {tag!r}")
        return cls(binary)
    if code == _EXT_STRUCT:
        tag, fields = msgpack.unpackb(
            data, raw=False, use_list=True, ext_hook=_ext_hook, strict_map_key=False)
        entry = _STRUCTS.get(tag)
        if entry is None:
            raise WireError(f"unknown wire struct tag {tag!r}")
        cls, known, decode = entry
        fields = {k: v for k, v in fields.items() if k in known}
        return decode(fields) if decode is not None else cls(**fields)
    if code == _EXT_SET:
        return set(msgpack.unpackb(
            data, raw=False, use_list=True, ext_hook=_ext_hook, strict_map_key=False))
    if code == _EXT_NDARRAY:
        import numpy as np

        dtype_str, shape, raw = msgpack.unpackb(data, raw=False, use_list=True)
        return np.frombuffer(raw, dtype=np.dtype(dtype_str)).reshape(shape)
    raise WireError(f"unknown wire ext code {code}")


def dumps(obj: Any) -> bytes:
    """Encode a control-plane message. Raises WireError on unregistered types."""
    if not _STRUCTS:
        _register_builtin_types()
    try:
        return msgpack.packb(obj, use_bin_type=True, default=_default)
    except WireError:
        raise
    except (TypeError, ValueError) as e:
        raise WireError(f"cannot wire-encode {type(obj).__name__}: {e}") from e


def loads(blob: bytes) -> Any:
    """Decode a control-plane message. Never executes code; raises WireError
    on malformed input (including pickle blobs)."""
    if not _STRUCTS:
        _register_builtin_types()
    if not blob:
        return None
    try:
        return msgpack.unpackb(
            blob, raw=False, use_list=True, ext_hook=_ext_hook, strict_map_key=False)
    except WireError:
        raise
    except Exception as e:
        raise WireError(f"malformed wire payload: {e}") from e

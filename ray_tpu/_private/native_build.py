"""Shared g++ build-on-first-use helper for the native components
(arena store, data loader). Rebuilds when the source is newer than the
cached .so; a corrupt/foreign .so falls back to rebuild, then to None so
callers can use their Python fallbacks."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

_lock = threading.Lock()
_cache: dict = {}


def build_and_load(src: str, lib_path: str,
                   extra_flags: Sequence[str] = ()) -> Optional[ctypes.CDLL]:
    with _lock:
        key = lib_path
        if key in _cache:
            return _cache[key]

        def _build() -> bool:
            os.makedirs(os.path.dirname(lib_path), exist_ok=True)
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                   *extra_flags, src, "-o", lib_path + ".tmp"]
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=180)
                os.replace(lib_path + ".tmp", lib_path)
                return True
            except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                    OSError):
                return False

        def _stale() -> bool:
            try:
                return os.path.getmtime(src) > os.path.getmtime(lib_path)
            except OSError:
                return True

        lib = None
        if not os.path.exists(lib_path) or _stale():
            _build()
        if os.path.exists(lib_path):
            try:
                lib = ctypes.CDLL(lib_path)
            except OSError:
                # corrupt or wrong-arch artifact: rebuild once
                try:
                    os.unlink(lib_path)
                except OSError:  # raylint: disable=EXC001 rebuild below handles the stale artifact either way
                    pass
                if _build():
                    try:
                        lib = ctypes.CDLL(lib_path)
                    except OSError:
                        lib = None
        _cache[key] = lib
        return lib

"""GCS: the cluster control plane.

Reference: ``src/ray/gcs/gcs_server.cc`` (subsystem init at :266-294) — node
membership + health (``gcs_node_manager.cc``, ``gcs_health_check_manager.cc``),
resource view (``gcs_resource_manager.cc``), actor directory + fault tolerance
(``gcs_actor_manager.h``, ``gcs_actor_scheduler.cc``), placement groups with
2PC reserve/commit (``gcs_placement_group_manager.h``,
``gcs_placement_group_scheduler.h:115-118``), job table (``gcs_job_manager.cc``),
internal KV (``gcs_kv_manager.cc``), pubsub (``src/ray/pubsub``), and a
GCS-hosted object directory (deviation: the reference resolves object
locations via owners — ``ownership_object_directory.cc``; round 1 centralizes
the directory here and owners serve small objects directly).

TPU-first: node resources carry ``TPU`` chips and slice/topology labels, and
actor/PG scheduling can select on them (slice-affine gang scheduling).
"""

from __future__ import annotations

import asyncio
import logging
import pickle

from ray_tpu._private import wire
import time
from collections import deque
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private.common import (
    Bundle,
    NodeInfo,
    PlacementGroupSpec,
    TaskSpec,
    label_match,
    resources_ge,
)
from ray_tpu._private.config import RAY_CONFIG
from ray_tpu._private.async_util import spawn
from ray_tpu._private.task_events import TERMINAL_STATES
from ray_tpu._private.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu._private.rpc import RpcError, RpcServer, RetryingRpcClient, ServerConnection
from ray_tpu._private.store_client import make_store

logger = logging.getLogger("ray_tpu.gcs")


class ActorRecord:
    def __init__(self, actor_id: ActorID, spec: TaskSpec):
        self.actor_id = actor_id
        self.spec = spec
        opts = spec.actor_options
        self.name = opts.name or ""
        self.namespace = opts.namespace or "default"
        self.lifetime = opts.lifetime
        self.max_restarts = opts.max_restarts
        self.restarts_used = 0
        self.state = "PENDING_CREATION"
        self.address = ""
        self.node_id: Optional[NodeID] = None
        self.job_id = spec.job_id
        self.death_cause = ""
        self.class_name = ""
        self.pending_kill = False
        self.lease_id = ""

    def dump(self) -> dict:
        """Durable form for the store client (replayed on GCS restart)."""
        return {
            "spec": self.spec,
            "state": self.state,
            "address": self.address,
            "node_id": self.node_id.binary() if self.node_id else None,
            "restarts_used": self.restarts_used,
            "death_cause": self.death_cause,
            "class_name": self.class_name,
            "pending_kill": self.pending_kill,
            "lease_id": self.lease_id,
        }

    @classmethod
    def restore(cls, data: dict) -> "ActorRecord":
        spec: TaskSpec = data["spec"]
        record = cls(spec.actor_id, spec)
        record.state = data["state"]
        record.address = data["address"]
        record.node_id = NodeID(data["node_id"]) if data["node_id"] else None
        record.restarts_used = data["restarts_used"]
        record.death_cause = data["death_cause"]
        record.class_name = data["class_name"]
        record.pending_kill = data["pending_kill"]
        record.lease_id = data.get("lease_id", "")
        return record

    def info(self) -> dict:
        return {
            "actor_id": self.actor_id.hex(),
            "state": self.state,
            "address": self.address,
            "node_id": self.node_id.hex() if self.node_id else "",
            "name": self.name,
            "namespace": self.namespace,
            "restarts_used": self.restarts_used,
            "max_restarts": self.max_restarts,
            "death_cause": self.death_cause,
            "class_name": self.class_name,
            "job_id": self.job_id.hex(),
            "lifetime": self.lifetime,
        }


class PGRecord:
    def __init__(self, spec: PlacementGroupSpec):
        self.spec = spec
        self.state = "PENDING"  # PENDING | CREATED | REMOVED | RESCHEDULING
        self.bundle_nodes: List[Optional[NodeID]] = [None] * len(spec.bundles)
        self.ready_event = asyncio.Event()

    def dump(self) -> dict:
        return {
            "spec": self.spec,
            "state": self.state,
            "bundle_nodes": [n.binary() if n else None for n in self.bundle_nodes],
        }

    @classmethod
    def restore(cls, data: dict) -> "PGRecord":
        pg = cls(data["spec"])
        pg.state = data["state"]
        pg.bundle_nodes = [NodeID(b) if b else None for b in data["bundle_nodes"]]
        if pg.state in ("CREATED", "REMOVED"):
            pg.ready_event.set()
        return pg


class GcsTaskManager:
    """Bounded per-job store of task lifecycle events.

    Reference: ``gcs/gcs_server/gcs_task_manager.cc`` — core workers flush
    batched state transitions here; the store keeps a bounded per-job ring
    (drop-oldest + a drop counter so truncation is visible, mirroring
    ``RAY_task_events_max_num_task_in_gcs``), merges owner-side and
    executor-side events by task id, and serves ``ray list tasks`` /
    ``ray summary tasks`` / the dashboard timeline."""

    def __init__(self, max_per_job: Optional[int] = None,
                 max_events_per_task: Optional[int] = None):
        self.max_per_job = max_per_job or RAY_CONFIG.gcs_task_events_max_per_job
        self.max_events_per_task = (max_events_per_task
                                    or RAY_CONFIG.task_events_max_per_task)
        # job_hex -> {task_id_hex: record}, insertion-ordered (dict) so the
        # oldest task evicts first when the ring is full
        self.jobs: Dict[str, Dict[str, dict]] = {}
        # flat id index: owner and executor flush independently (the
        # executor's RUNNING may even arrive first), and the lookup runs
        # once per event — it must be O(1), not a scan over every ring
        self._by_tid: Dict[str, dict] = {}
        self.dropped: Dict[str, int] = {}  # per-job: ring evictions +
        #                                    reporter-side buffer drops

    def add_events(self, events: List[dict], dropped: int = 0):
        for ev in events:
            tid = ev.get("task_id")
            if not tid:
                continue
            rec = self._by_tid.get(tid)
            if rec is None:
                job = ev.get("job_id") or "unknown"
                ring = self.jobs.setdefault(job, {})
                while len(ring) >= self.max_per_job:
                    oldest = next(iter(ring))
                    del ring[oldest]
                    self._by_tid.pop(oldest, None)
                    self.dropped[job] = self.dropped.get(job, 0) + 1
                rec = ring[tid] = self._by_tid[tid] = {
                    "task_id": tid, "job_id": job, "name": "", "state": "",
                    "attempt": 0, "error": "", "worker": "", "node": "",
                    "arg_bytes": 0, "ret_bytes": 0,
                    "events": [], "_last_ts": 0.0,
                }
            self._merge(rec, ev)
        if dropped:
            self.dropped["_reporter"] = self.dropped.get("_reporter", 0) + dropped

    def _find(self, tid: str) -> Optional[dict]:
        return self._by_tid.get(tid)

    def _merge(self, rec: dict, ev: dict):
        entry = {"state": ev["state"], "ts": ev["ts"],
                 "attempt": ev.get("attempt", 0)}
        if ev.get("error"):
            entry["error"] = ev["error"]
        events = rec["events"]
        events.append(entry)
        if len(events) > self.max_events_per_task:
            del events[: len(events) - self.max_events_per_task]
        if ev.get("name"):
            rec["name"] = ev["name"]
        if ev.get("worker"):
            rec["worker"] = ev["worker"]
        if ev.get("node"):
            rec["node"] = ev["node"]
        if ev.get("error"):
            rec["error"] = ev["error"]
        # object-size accounting: arg bytes ride SUBMITTED, return bytes
        # the terminal event; max() keeps the merge idempotent under
        # replays and retry re-submissions report their largest attempt
        if ev.get("arg_bytes"):
            rec["arg_bytes"] = max(rec["arg_bytes"], int(ev["arg_bytes"]))
        if ev.get("ret_bytes"):
            rec["ret_bytes"] = max(rec["ret_bytes"], int(ev["ret_bytes"]))
        rec["attempt"] = max(rec["attempt"], ev.get("attempt", 0))
        # latest-state resolution: owner and executor flush independently,
        # so events can arrive out of ts order; a terminal state is never
        # overridden by a late RUNNING
        if ev["state"] in TERMINAL_STATES or (
                rec["state"] not in TERMINAL_STATES
                and ev["ts"] >= rec["_last_ts"]):
            rec["state"] = ev["state"]
        rec["_last_ts"] = max(rec["_last_ts"], ev["ts"])

    @staticmethod
    def _dump(rec: dict) -> dict:
        events = sorted(rec["events"], key=lambda e: e["ts"])
        out = {k: v for k, v in rec.items() if not k.startswith("_")}
        out["events"] = events
        if events:
            out["start_ts"] = events[0]["ts"]
            out["end_ts"] = events[-1]["ts"]
            out["duration_s"] = events[-1]["ts"] - events[0]["ts"]
        return out

    def list_tasks(self, job_id: Optional[str] = None,
                   name: Optional[str] = None, state: Optional[str] = None,
                   limit: int = 200) -> List[dict]:
        out = []
        for job, ring in self.jobs.items():
            if job_id and job != job_id:
                continue
            for rec in ring.values():
                # substring match: function names are qualnames
                # ("mod.<locals>.fn"), exact equality would be unusable
                if name and name not in rec["name"]:
                    continue
                if state and rec["state"] != state:
                    continue
                out.append(self._dump(rec))
        out.sort(key=lambda r: r.get("start_ts", 0.0))
        return out[-int(limit):]

    def get_task(self, tid: str) -> Optional[dict]:
        rec = self._find(tid)
        return self._dump(rec) if rec is not None else None

    def summarize(self, job_id: Optional[str] = None) -> dict:
        """Per-function counts by lifecycle state (the ``ray summary
        tasks`` analog), plus per-function object-size accounting
        (summed serialized argument / returned-object bytes)."""
        per_fn: Dict[str, Dict[str, int]] = {}
        sizes: Dict[str, Dict[str, int]] = {}
        total = 0
        for job, ring in self.jobs.items():
            if job_id and job != job_id:
                continue
            for rec in ring.values():
                total += 1
                fn = rec["name"] or "<unknown>"
                by_state = per_fn.setdefault(fn, {})
                st = rec["state"] or "UNKNOWN"
                by_state[st] = by_state.get(st, 0) + 1
                sz = sizes.setdefault(fn, {"arg_bytes": 0, "ret_bytes": 0})
                sz["arg_bytes"] += rec.get("arg_bytes", 0)
                sz["ret_bytes"] += rec.get("ret_bytes", 0)
        return {"per_function": per_fn, "per_function_bytes": sizes,
                "total": total, "dropped": dict(self.dropped)}


class ShardedTaskEvents:
    """Sharded + pipelined front for ``GcsTaskManager``.

    5k+ tasks/s of lifecycle events must not serialize on one merge path:
    ``AddTaskEvents`` routes each event by task-id hash into one of
    ``gcs_task_event_shards`` bounded ingest queues and returns immediately;
    one drain task per shard merges in the background (so a burst costs the
    caller an enqueue, not a merge), and reads fan out over the shards.
    Per-shard rings keep the global per-job bound at
    ``gcs_task_events_max_per_job`` in aggregate."""

    def __init__(self, nshards: Optional[int] = None):
        n = max(1, nshards or RAY_CONFIG.gcs_task_event_shards)
        per_shard_cap = max(1, RAY_CONFIG.gcs_task_events_max_per_job // n)
        self.shards = [GcsTaskManager(max_per_job=per_shard_cap)
                       for _ in range(n)]
        self._queues: List[deque] = [deque() for _ in range(n)]
        self._wake = [asyncio.Event() for _ in range(n)]
        self._qmax = max(256, RAY_CONFIG.gcs_task_event_ingest_max)
        self._flush_rr = 0  # rotating start shard for bounded read flushes
        self.ingest_dropped = 0  # queue-full drops (visible in summarize)
        self.batches = 0  # drained merge batches (pipelining evidence)

    def _shard_of(self, tid: str) -> int:
        # task ids are hex; the tail bytes are well distributed
        try:
            return int(tid[-4:], 16) % len(self.shards)
        except (ValueError, TypeError):
            return 0

    def ingest(self, events: List[dict], dropped: int = 0):
        """Handler-side: route + enqueue, no merging on the RPC path."""
        for ev in events:
            tid = ev.get("task_id")
            if not tid:
                continue
            i = self._shard_of(tid)
            q = self._queues[i]
            if len(q) >= self._qmax:
                # drop-OLDEST, matching the store rings: the newest events
                # carry the terminal FINISHED/FAILED transitions that must
                # win the merge — shedding them would freeze tasks at
                # RUNNING forever in every surface
                q.popleft()
                self.ingest_dropped += 1
            q.append(ev)
            self._wake[i].set()
        if dropped:
            self.shards[0].add_events([], dropped)

    async def drain_loop(self, i: int):
        """One per shard: merge queued events in batches."""
        q, wake, shard = self._queues[i], self._wake[i], self.shards[i]
        while True:
            await wake.wait()
            wake.clear()
            while q:
                batch = []
                while q and len(batch) < 512:
                    batch.append(q.popleft())
                shard.add_events(batch)
                self.batches += 1
                # yield between batches: reads and other RPCs interleave
                await asyncio.sleep(0)

    def flush_sync(self, max_events: int = 20000):
        """Read-your-writes for the read RPCs: merge what is queued, but
        BOUNDED — under a sustained overload the queues can hold hundreds
        of thousands of events, and merging them all inside one read
        handler would stall the whole GCS loop (heartbeats, leases). The
        start shard rotates per call so the budget doesn't systematically
        favor low-index shards under overload. In the normal case the
        drain tasks keep queues near-empty and this merges everything."""
        budget = max_events
        n = len(self._queues)
        self._flush_rr = (self._flush_rr + 1) % n
        for k in range(n):
            if budget <= 0:
                break
            budget -= self.flush_shard((self._flush_rr + k) % n, budget)

    def flush_shard(self, i: int, budget: int = 20000) -> int:
        """Merge up to ``budget`` queued events of ONE shard; returns the
        number merged (get_task only needs its task's shard current)."""
        q = self._queues[i]
        batch = []
        while q and len(batch) < budget:
            batch.append(q.popleft())
        if batch:
            self.shards[i].add_events(batch)
        return len(batch)

    # -- reads fan out over the shards ---------------------------------

    def add_events(self, events: List[dict], dropped: int = 0):
        """Synchronous compatibility path (bypasses the ingest queues)."""
        for ev in events:
            tid = ev.get("task_id")
            if tid:
                self.shards[self._shard_of(tid)].add_events([ev])
        if dropped:
            self.shards[0].add_events([], dropped)

    def list_tasks(self, job_id=None, name=None, state=None,
                   limit: int = 200) -> List[dict]:
        out = []
        for shard in self.shards:
            out.extend(shard.list_tasks(job_id=job_id, name=name,
                                        state=state, limit=limit))
        out.sort(key=lambda r: r.get("start_ts", 0.0))
        return out[-int(limit):]

    def get_task(self, tid: str) -> Optional[dict]:
        return self.shards[self._shard_of(tid)].get_task(tid)

    def summarize(self, job_id=None) -> dict:
        per_fn: Dict[str, Dict[str, int]] = {}
        sizes: Dict[str, Dict[str, int]] = {}
        dropped: Dict[str, int] = {}
        total = 0
        for shard in self.shards:
            s = shard.summarize(job_id=job_id)
            total += s["total"]
            for fn, by_state in s["per_function"].items():
                agg = per_fn.setdefault(fn, {})
                for st, n in by_state.items():
                    agg[st] = agg.get(st, 0) + n
            for fn, sz in s["per_function_bytes"].items():
                agg_sz = sizes.setdefault(fn, {"arg_bytes": 0, "ret_bytes": 0})
                agg_sz["arg_bytes"] += sz["arg_bytes"]
                agg_sz["ret_bytes"] += sz["ret_bytes"]
            for k, v in s["dropped"].items():
                dropped[k] = dropped.get(k, 0) + v
        if self.ingest_dropped:
            dropped["_ingest_queue"] = self.ingest_dropped
        return {"per_function": per_fn, "per_function_bytes": sizes,
                "total": total, "dropped": dropped,
                "shards": len(self.shards), "merge_batches": self.batches}


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, persist_dir: str = ""):
        self.store = make_store(persist_dir)
        self.server = RpcServer(self._handle, host, port)
        self.server.on_disconnect = self._on_disconnect
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.node_available: Dict[NodeID, Dict[str, float]] = {}
        # last availability broadcast per node (delta suppression for the
        # resource_view syncer stream; reference: ray_syncer.h:89)
        self._last_view_pub: Dict[NodeID, Dict[str, float]] = {}
        self.node_last_seen: Dict[NodeID, float] = {}
        self.node_clients: Dict[NodeID, RetryingRpcClient] = {}
        self.kv: Dict[Tuple[str, str], bytes] = {}
        self.actors: Dict[ActorID, ActorRecord] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.pgs: Dict[PlacementGroupID, PGRecord] = {}
        self.jobs: Dict[JobID, dict] = {}
        self.job_counter = 0
        # oid -> {"attempt": committed execution epoch, "nodes": holders};
        # seal-once at cluster scope: only the newest attempt's copies are
        # visible, displaced copies are deleted at their nodes (reference:
        # plasma's seal-once, obj_lifecycle_mgr.cc)
        self.object_dir: Dict[bytes, dict] = {}
        self._freed_ring: "deque[bytes]" = deque()  # bounded tombstone FIFO
        self.subs: Dict[int, Tuple[ServerConnection, Set[str]]] = {}
        self.conn_jobs: Dict[int, JobID] = {}
        self._worker_clients: Dict[str, RetryingRpcClient] = {}
        # unplaceable demand shapes -> autoscaler (reference: the v2
        # gcs_autoscaler_state_manager.cc cluster-state view)
        self.pending_demands: Dict[tuple, dict] = {}
        self.node_last_used: Dict[NodeID, float] = {}
        self.node_num_leases: Dict[NodeID, int] = {}
        # structured event ring (reference: util/event.cc + export events
        # aggregated by the dashboard) — bounded, newest at the right
        self.events = deque(maxlen=1000)
        # task lifecycle events, sharded + pipelined (reference:
        # gcs_task_manager.cc; the sharding is ours — see ShardedTaskEvents)
        self.task_manager = ShardedTaskEvents()
        self._background: List[asyncio.Task] = []
        self.start_time = time.time()
        self._load_init_data()

    # ------------------------------------------------------------------
    # persistence (reference: gcs_init_data.cc replay + store_client/)
    # ------------------------------------------------------------------

    def _load_init_data(self):
        """Reload all durable tables from the store (no-op for a fresh
        in-memory store). Reference: GcsServer::Start loads GcsInitData
        before DoStart (gcs_server.cc:212)."""
        for key, blob in self.store.all("kv").items():
            ns, _, k = key.partition("\x00")
            self.kv[(ns, k)] = wire.loads(blob)
        for key, blob in self.store.all("nodes").items():
            info: NodeInfo = wire.loads(blob)
            self.nodes[info.node_id] = info
            if info.alive:
                self.node_available[info.node_id] = dict(info.total_resources)
                # grace period: raylets heartbeat in; health check reaps others
                self.node_last_seen[info.node_id] = time.monotonic()
                self.node_clients[info.node_id] = RetryingRpcClient(info.address)
        for key, blob in self.store.all("actors").items():
            record = ActorRecord.restore(wire.loads(blob))
            self.actors[record.actor_id] = record
            if record.name and record.state != "DEAD":
                self.named_actors[(record.namespace, record.name)] = record.actor_id
        for key, blob in self.store.all("pgs").items():
            pg = PGRecord.restore(wire.loads(blob))
            self.pgs[pg.spec.pg_id] = pg
        for key, blob in self.store.all("jobs").items():
            job = wire.loads(blob)
            self.jobs[JobID.from_hex(job["job_id"])] = job
        counter = self.store.get("meta", "job_counter")
        if counter is not None:
            self.job_counter = wire.loads(counter)
        if self.actors or self.nodes:
            logger.info(
                "GCS init data replayed: %d nodes, %d actors, %d pgs, %d jobs, %d kv",
                len(self.nodes), len(self.actors), len(self.pgs), len(self.jobs),
                len(self.kv))

    def _persist_kv(self, ns: str, key: str, value=None, delete: bool = False):
        skey = f"{ns}\x00{key}"
        if delete:
            self.store.delete("kv", skey)
        else:
            self.store.put("kv", skey, wire.dumps(value))

    def _persist_node(self, info: NodeInfo):
        if not info.alive:
            self.store.delete("nodes", info.node_id.hex())
        else:
            self.store.put("nodes", info.node_id.hex(), wire.dumps(info))

    def _persist_actor(self, record: ActorRecord):
        if record.state == "DEAD":
            # terminal: delete rather than replay-forever (the in-memory
            # record still serves info queries until the next restart)
            self.store.delete("actors", record.actor_id.hex())
        else:
            self.store.put("actors", record.actor_id.hex(),
                           wire.dumps(record.dump()))

    def _persist_pg(self, pg: PGRecord):
        if pg.state == "REMOVED":
            self.store.delete("pgs", pg.spec.pg_id.hex())
        else:
            self.store.put("pgs", pg.spec.pg_id.hex(), wire.dumps(pg.dump()))

    def _persist_job(self, job: dict):
        if job["state"] == "FINISHED":
            self.store.delete("jobs", job["job_id"])
        else:
            self.store.put("jobs", job["job_id"], wire.dumps(job))

    async def start(self) -> str:
        addr = await self.server.start()
        self._background.append(spawn(self._health_check_loop(),
                                      what="gcs health-check loop"))
        for i in range(len(self.task_manager.shards)):
            self._background.append(spawn(
                self.task_manager.drain_loop(i),
                what=f"task-event drain shard {i}"))
        # resume interrupted scheduling work from replayed init data
        for record in self.actors.values():
            if record.state in ("PENDING_CREATION", "RESTARTING"):
                if record.address:
                    # a creation was in flight when we died: probe before
                    # rescheduling so we never run two instances
                    spawn(self._recover_creating_actor(record),
                          what="actor creation recovery")
                else:
                    spawn(self._schedule_actor(record), what="actor scheduling")
        for job_id, job in list(self.jobs.items()):
            if job["state"] == "RUNNING":
                spawn(self._reap_job_if_driver_gone(job_id, job),
                      what="job reap probe")
        for pg in self.pgs.values():
            if pg.state in ("PENDING", "RESCHEDULING"):
                spawn(self._schedule_pg(pg), what="placement-group scheduling")
        logger.info("GCS listening on %s", addr)
        return addr

    async def stop(self):
        for t in self._background:
            t.cancel()
        await self.server.stop()
        self.store.close()

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    async def _handle(self, method: str, payload: bytes, conn) -> bytes:
        fn = getattr(self, f"_rpc_{method}", None)
        if fn is None:
            raise RpcError(f"GCS: unknown method {method}")
        req = wire.loads(payload) if payload else {}
        resp = await fn(req, conn)
        return wire.dumps(resp)

    def _publish(self, channel: str, message: dict):
        payload = wire.dumps(message)
        for conn, channels in list(self.subs.values()):
            if channel in channels:
                spawn(conn.push(channel, payload), what="pubsub push")

    async def _on_disconnect(self, conn: ServerConnection):
        self.subs.pop(conn.conn_id, None)
        job_id = self.conn_jobs.pop(conn.conn_id, None)
        if job_id is not None and job_id in self.jobs:
            await self._finish_job(job_id)

    # ------------------------------------------------------------------
    # nodes / health
    # ------------------------------------------------------------------

    async def _rpc_RegisterNode(self, req, conn):
        info: NodeInfo = req["info"]
        self.nodes[info.node_id] = info
        self.node_available[info.node_id] = dict(info.total_resources)
        self.node_last_seen[info.node_id] = time.monotonic()
        self.node_clients[info.node_id] = RetryingRpcClient(info.address)
        self._persist_node(info)
        logger.info("node %s registered: %s labels=%s", info.node_id.hex()[:8],
                    info.total_resources, info.labels)
        self._publish("nodes", {"event": "added", "node": info.to_dict()})
        self._publish("resource_view", self._view_entry(info.node_id))
        self._record_event("node", "INFO", "node registered",
                           node_id=info.node_id.hex(),
                           resources=dict(info.total_resources))
        return {"status": "ok"}

    async def _rpc_Heartbeat(self, req, conn):
        node_id: NodeID = req["node_id"]
        if node_id not in self.nodes:
            return {"status": "unknown_node"}  # raylet should re-register
        self.node_last_seen[node_id] = time.monotonic()
        self.node_available[node_id] = req["available"]
        self.node_num_leases[node_id] = req.get("num_leases", 0)
        if self._node_used(node_id) or node_id not in self.node_last_used:
            self.node_last_used[node_id] = time.monotonic()
        # syncer: broadcast availability DELTAS to subscribed raylets so
        # their local schedulers can spill leases peer-to-peer without a
        # per-lease GCS round trip (reference: ray_syncer.h:89 resource
        # views over bidi streams; here piggybacked on 1 Hz heartbeats)
        if self._last_view_pub.get(node_id) != req["available"]:
            self._last_view_pub[node_id] = dict(req["available"])
            self._publish("resource_view", self._view_entry(node_id))
        # parked lease shapes feed the autoscaler's demand view (the
        # two-level path no longer touches PickNode for schedulable work)
        for shape in req.get("pending_shapes", ()):
            self._record_demand(shape["resources"], shape.get("selector", {}),
                                shape.get("waiter_id", ""))
        return {"status": "ok"}

    def _view_entry(self, node_id: NodeID) -> dict:
        info = self.nodes[node_id]
        return {
            "node_id": node_id.hex(),
            "address": info.address,
            "available": dict(self.node_available.get(node_id, {})),
            "total": dict(info.total_resources),
            "labels": dict(info.labels),
            "alive": info.alive,
        }

    async def _rpc_GetAllNodes(self, req, conn):
        return {"nodes": [
            {**n.to_dict(),
             "available": dict(self.node_available.get(n.node_id, {}))}
            for n in self.nodes.values()]}

    async def _rpc_GetClusterResources(self, req, conn):
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for nid, info in self.nodes.items():
            if not info.alive:
                continue
            for k, v in info.total_resources.items():
                total[k] = total.get(k, 0.0) + v
            for k, v in self.node_available.get(nid, {}).items():
                avail[k] = avail.get(k, 0.0) + v
        return {"total": total, "available": avail}

    async def _rpc_DrainNode(self, req, conn):
        node_id: NodeID = req["node_id"]
        await self._mark_node_dead(node_id, "drained")
        return {"status": "ok"}

    async def _health_check_loop(self):
        period = RAY_CONFIG.health_check_period_ms / 1000.0
        timeout = RAY_CONFIG.health_check_timeout_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node_id, info in list(self.nodes.items()):
                if info.alive and now - self.node_last_seen.get(node_id, now) > timeout:
                    await self._mark_node_dead(node_id, "health check timeout")

    async def _mark_node_dead(self, node_id: NodeID, reason: str):
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        self.node_available.pop(node_id, None)
        self._persist_node(info)
        logger.warning("node %s dead: %s", node_id.hex()[:8], reason)
        self._publish("nodes", {"event": "removed", "node_id": node_id.hex(), "reason": reason})
        self._publish("resource_view", self._view_entry(node_id))
        self._record_event("node", "ERROR", f"node dead: {reason}",
                           node_id=node_id.hex())
        # drop object locations on that node; keep the committed-attempt
        # tombstone so a partitioned zombie's stale announce can't
        # re-register an older epoch as current
        for oid, entry in list(self.object_dir.items()):
            entry["nodes"].discard(node_id)
        # fail over actors that lived there
        for record in list(self.actors.values()):
            if record.node_id == node_id and record.state in ("ALIVE", "PENDING_CREATION"):
                await self._on_actor_worker_lost(record, f"node died: {reason}")
        # reschedule placement groups with bundles there
        for pg in self.pgs.values():
            if pg.state == "CREATED" and any(n == node_id for n in pg.bundle_nodes):
                pg.state = "RESCHEDULING"
                spawn(self._schedule_pg(pg), what="placement-group scheduling")

    # ------------------------------------------------------------------
    # kv
    # ------------------------------------------------------------------

    async def _rpc_KVPut(self, req, conn):
        key = (req.get("ns", ""), req["key"])
        if not req.get("overwrite", True) and key in self.kv:
            return {"added": False}
        self.kv[key] = req["value"]
        self._persist_kv(key[0], key[1], req["value"])
        return {"added": True}

    async def _rpc_KVGet(self, req, conn):
        return {"value": self.kv.get((req.get("ns", ""), req["key"]))}

    async def _rpc_KVMultiPut(self, req, conn):
        """Batched puts: N keys (possibly across namespaces) in one round
        trip, so high-rate mirrors (metrics, pool stats, store stats) don't
        serialize one handler dispatch per key."""
        added = 0
        for item in req.get("items") or ():
            key = (item.get("ns", ""), item["key"])
            self.kv[key] = item["value"]
            self._persist_kv(key[0], key[1], item["value"])
            added += 1
        return {"added": added}

    async def _rpc_KVMultiGet(self, req, conn):
        ns = req.get("ns", "")
        return {"values": {k: self.kv.get((ns, k))
                           for k in req.get("keys") or ()}}

    async def _rpc_KVDel(self, req, conn):
        prefix = req.get("prefix", False)
        ns = req.get("ns", "")
        if prefix:
            keys = [k for k in self.kv if k[0] == ns and k[1].startswith(req["key"])]
            for k in keys:
                del self.kv[k]
                self._persist_kv(k[0], k[1], delete=True)
            return {"deleted": len(keys)}
        if self.kv.pop((ns, req["key"]), None) is not None:
            self._persist_kv(ns, req["key"], delete=True)
            return {"deleted": 1}
        return {"deleted": 0}

    async def _rpc_KVKeys(self, req, conn):
        ns = req.get("ns", "")
        prefix = req.get("prefix", "")
        return {"keys": [k[1] for k in self.kv if k[0] == ns and k[1].startswith(prefix)]}

    # ------------------------------------------------------------------
    # jobs
    # ------------------------------------------------------------------

    async def _rpc_RegisterDriver(self, req, conn):
        self.job_counter += 1
        job_id = JobID.from_int(self.job_counter)
        self.jobs[job_id] = {
            "job_id": job_id.hex(),
            "driver_address": req.get("address", ""),
            "namespace": req.get("namespace", "default"),
            "start_time": time.time(),
            "state": "RUNNING",
            "entrypoint": req.get("entrypoint", ""),
        }
        self.conn_jobs[conn.conn_id] = job_id
        self.store.put("meta", "job_counter", wire.dumps(self.job_counter))
        self._persist_job(self.jobs[job_id])
        return {"job_id": job_id.binary()}

    async def _rpc_ReattachDriver(self, req, conn):
        """A driver re-binds its (new) connection to its existing job after a
        GCS restart, so driver-disconnect job cleanup keeps working."""
        job_id = JobID(req["job_id"])
        job = self.jobs.get(job_id)
        if job is not None and job["state"] == "RUNNING":
            self.conn_jobs[conn.conn_id] = job_id
            return {"status": "ok"}
        return {"status": "unknown_job"}

    async def _finish_job(self, job_id: JobID):
        job = self.jobs.get(job_id)
        if job is None or job["state"] == "FINISHED":
            return
        job["state"] = "FINISHED"
        job["end_time"] = time.time()
        self._persist_job(job)
        logger.info("job %s finished; reaping its actors", job_id.hex())
        for record in list(self.actors.values()):
            if record.job_id == job_id and record.lifetime != "detached" and record.state != "DEAD":
                await self._kill_actor(record, no_restart=True, reason="owning job finished")
        for pg in list(self.pgs.values()):
            if pg.spec.creator_job == job_id and pg.spec.lifetime != "detached":
                await self._remove_pg(pg)
        # purge the job's object-directory entries (incl. empty tombstones
        # kept for epoch fencing); ids embed the job id at the task-id tail
        from ray_tpu._private.ids import TaskID

        jid = job_id.binary()
        for oid in [o for o in self.object_dir
                    if o[TaskID.SIZE - len(jid) : TaskID.SIZE] == jid]:
            del self.object_dir[oid]

    # ------------------------------------------------------------------
    # pubsub
    # ------------------------------------------------------------------

    def _record_event(self, source: str, severity: str, message: str,
                      **metadata):
        event = {"ts": time.time(), "source": source, "severity": severity,
                 "message": message, "metadata": metadata}
        self.events.append(event)
        self._publish("events", event)

    async def _rpc_ReportEvent(self, req, conn):
        ev = dict(req["event"])
        self.events.append(ev)
        self._publish("events", ev)
        return {"status": "ok"}

    async def _rpc_GetEvents(self, req, conn):
        out = list(self.events)
        if req.get("source"):
            out = [e for e in out if e.get("source") == req["source"]]
        if req.get("severity"):
            want = str(req["severity"]).upper()
            out = [e for e in out if e.get("severity") == want]
        return {"events": out[-int(req.get("limit") or 200):]}

    # -- task lifecycle events (reference: gcs_task_manager.cc RPCs) --

    async def _rpc_AddTaskEvents(self, req, conn):
        # enqueue-and-return: the per-shard drain tasks merge in the
        # background so a 5k tasks/s burst costs each reporter an enqueue,
        # not a synchronous merge on the shared handler path
        self.task_manager.ingest(req.get("events") or [],
                                 int(req.get("dropped") or 0))
        return {"status": "ok"}

    async def _rpc_ListTasks(self, req, conn):
        self.task_manager.flush_sync()  # reads see everything enqueued
        return {"tasks": self.task_manager.list_tasks(
            job_id=req.get("job_id"), name=req.get("name"),
            state=req.get("state"), limit=int(req.get("limit") or 200))}

    async def _rpc_GetTask(self, req, conn):
        # only the one shard this task hashes to needs to be current
        tm = self.task_manager
        tm.flush_shard(tm._shard_of(req["task_id"]))
        return {"task": tm.get_task(req["task_id"])}

    async def _rpc_SummarizeTasks(self, req, conn):
        self.task_manager.flush_sync()
        return self.task_manager.summarize(job_id=req.get("job_id"))

    async def _rpc_Subscribe(self, req, conn):
        channels = set(req["channels"])
        existing = self.subs.get(conn.conn_id)
        if existing:
            existing[1].update(channels)
        else:
            self.subs[conn.conn_id] = (conn, channels)
        return {"status": "ok"}

    async def _rpc_Publish(self, req, conn):
        self._publish(req["channel"], req["message"])
        return {"status": "ok"}

    # ------------------------------------------------------------------
    # object directory
    # ------------------------------------------------------------------

    async def _rpc_ObjectLocAdd(self, req, conn):
        node_id = req["node_id"]
        attempt = req.get("attempt", 0)
        sizes = req.get("sizes") or {}
        for oid in req["oids"]:
            size = sizes.get(oid, 0)
            entry = self.object_dir.get(oid)
            if entry is not None and size:
                entry["size"] = size
            if entry is None:
                self.object_dir[oid] = {"attempt": attempt, "nodes": {node_id},
                                        "size": size}
            elif attempt > entry["attempt"]:
                displaced = entry["nodes"] - {node_id}
                self.object_dir[oid] = {"attempt": attempt, "nodes": {node_id},
                                        "size": size or entry.get("size", 0)}
                if displaced:
                    spawn(self._delete_stale_copies(oid, attempt, displaced),
                          what="stale-copy delete")
            elif attempt == entry["attempt"]:
                entry["nodes"].add(node_id)
            else:
                # stale-epoch announce: reject, and tell that node to drop it
                spawn(self._delete_stale_copies(
                    oid, entry["attempt"], {node_id}), what="stale-copy delete")
        return {"status": "ok"}

    async def _delete_stale_copies(self, oid: bytes, attempt: int, nodes):
        for node_id in nodes:
            client = self.node_clients.get(node_id)
            info = self.nodes.get(node_id)
            if client is None or info is None or not info.alive:
                continue
            try:
                await client.call("StoreDeleteStale", wire.dumps(
                    {"oid": oid, "attempt": attempt}), timeout=10.0, retries=1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("StoreDeleteStale(%s) to %s failed: %s",
                             oid.hex()[:8], node_id.hex()[:8], e)

    async def _rpc_ObjectLocRemove(self, req, conn):
        for oid in req["oids"]:
            entry = self.object_dir.get(oid)
            if entry:
                # keep the committed-attempt tombstone (empty node set) so a
                # stale-epoch announce can't re-register; purged at job end
                entry["nodes"].discard(req["node_id"])
        return {"status": "ok"}

    _FREED_EPOCH = 1 << 62  # tombstone attempt: beats any real epoch

    async def _rpc_ObjectFree(self, req, conn):
        """Owner-initiated cluster-wide free: zero references remain, so the
        copies on every holding node are deleted and the entry becomes a
        freed tombstone (reference: the owner's delete fan-out on ref-count
        zero). The tombstone's infinite epoch makes any late announce (e.g.
        a pull that completed mid-free) route into the stale-copy deletion
        path instead of resurrecting the object.

        Tombstones are BOUNDED: a FIFO ring of gcs_freed_tombstone_cap ids
        (oldest evicted first), not held until job end — a long-running job
        with high object churn would otherwise grow the directory without
        limit. Evicting a tombstone only re-opens the (already tiny) window
        for an announce delayed past tens of thousands of subsequent frees."""
        per_node: Dict[NodeID, List[bytes]] = {}
        for oid in req["oids"]:
            entry = self.object_dir.get(oid)
            if entry:
                for node_id in entry["nodes"]:
                    per_node.setdefault(node_id, []).append(oid)
            self.object_dir[oid] = {"attempt": self._FREED_EPOCH,
                                    "nodes": set()}
            self._freed_ring.append(oid)
        cap = RAY_CONFIG.gcs_freed_tombstone_cap
        while len(self._freed_ring) > cap:
            old = self._freed_ring.popleft()
            stale = self.object_dir.get(old)
            if stale is not None and stale["attempt"] == self._FREED_EPOCH:
                del self.object_dir[old]
        for node_id, oids in per_node.items():
            client = self.node_clients.get(node_id)
            info = self.nodes.get(node_id)
            if client is None or info is None or not info.alive:
                continue
            try:
                await client.call("StoreDelete", wire.dumps({"oids": oids}),
                                  timeout=10.0, retries=1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("StoreDelete(%d oids) to %s failed: %s",
                             len(oids), node_id.hex()[:8], e)
        return {"status": "ok"}

    async def _rpc_ObjectLocGet(self, req, conn):
        out = []
        entry = self.object_dir.get(req["oid"])
        for node_id in (entry["nodes"] if entry else ()):  # alive nodes only
            info = self.nodes.get(node_id)
            if info is not None and info.alive:
                out.append({"node_id": node_id.hex(), "address": info.address})
        return {"locations": out, "attempt": entry["attempt"] if entry else 0,
                "size": entry.get("size", 0) if entry else 0}

    # ------------------------------------------------------------------
    # scheduling helpers
    # ------------------------------------------------------------------

    def _feasible_nodes(self, resources: Dict[str, float], selector: Dict[str, str],
                        check_available: bool = True) -> List[NodeID]:
        out = []
        for node_id, info in self.nodes.items():
            if not info.alive:
                continue
            if selector and not label_match(info.labels, selector):
                continue
            pool = self.node_available.get(node_id, {}) if check_available else info.total_resources
            if resources_ge(pool, resources):
                out.append(node_id)
        return out

    def _pick_node(self, resources: Dict[str, float], selector: Dict[str, str],
                   waiter_id: str = "") -> Optional[NodeID]:
        """Hybrid policy: pack onto the most-utilized feasible node below the
        spread threshold, else least-utilized (reference:
        raylet/scheduling/policy/hybrid_scheduling_policy.cc)."""
        feasible = self._feasible_nodes(resources, selector)
        if not feasible:
            # fall back to nodes that are feasible by total resources (queue there)
            feasible = self._feasible_nodes(resources, selector, check_available=False)
            if not feasible:
                self._record_demand(resources, selector, waiter_id)
                return None
        def utilization(nid):
            info = self.nodes[nid]
            avail = self.node_available.get(nid, {})
            fracs = [
                1.0 - avail.get(k, 0.0) / v
                for k, v in info.total_resources.items()
                if v > 0
            ]
            return max(fracs) if fracs else 0.0
        scored = sorted(feasible, key=lambda nid: (utilization(nid), nid.hex()))
        threshold = RAY_CONFIG.scheduler_spread_threshold
        packed = [nid for nid in scored if utilization(nid) < threshold]
        if packed:
            return packed[-1]  # most utilized below threshold -> pack
        return scored[0]  # least utilized -> spread

    async def _rpc_PickNode(self, req, conn):
        """Owner-side lease policy support: pick a node for a task's resource
        shape + label selector (reference: owner lease_policy.cc + raylet
        spillback; centralized here on the GCS resource view)."""
        strat = req.get("strategy")
        if strat == "SPREAD":
            feasible = self._feasible_nodes(req["resources"], req.get("selector", {}))
            if feasible:
                idx = req.get("spread_hint", 0) % len(feasible)
                nid = sorted(feasible, key=lambda n: n.hex())[idx]
                return {"node": self._node_addr(nid)}
        nid = self._pick_node(req["resources"], req.get("selector", {}),
                              waiter_id=req.get("waiter_id", ""))
        return {"node": self._node_addr(nid) if nid else None}

    def _node_addr(self, nid: NodeID) -> dict:
        info = self.nodes[nid]
        return {"node_id": nid.hex(), "address": info.address}

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    def _worker_client(self, address: str) -> RetryingRpcClient:
        client = self._worker_clients.get(address)
        if client is None:
            client = RetryingRpcClient(address)
            self._worker_clients[address] = client
        return client

    async def _rpc_CreateActor(self, req, conn):
        spec: TaskSpec = req["spec"]
        opts = spec.actor_options
        if opts.name:
            key = (opts.namespace or "default", opts.name)
            existing = self.named_actors.get(key)
            if existing is not None and self.actors[existing].state != "DEAD":
                if opts.get_if_exists:
                    return {"status": "exists", "info": self.actors[existing].info()}
                return {"status": "name_taken"}
        actor_id = spec.actor_id
        record = ActorRecord(actor_id, spec)
        record.class_name = req.get("class_name", "")
        self.actors[actor_id] = record
        if record.name:
            self.named_actors[(record.namespace, record.name)] = actor_id
        self._persist_actor(record)
        spawn(self._schedule_actor(record), what="actor scheduling")
        return {"status": "ok", "info": record.info()}

    async def _schedule_actor(self, record: ActorRecord):
        """Lease a worker on a feasible node and push the creation task.

        Reference: gcs_actor_scheduler.cc (lease-based actor scheduling).
        """
        spec = record.spec
        opts = spec.actor_options
        resources = opts.required_resources()
        deadline = time.monotonic() + 3600.0
        warned = False
        while record.state in ("PENDING_CREATION", "RESTARTING") and not record.pending_kill:
            node_id = None
            if opts.placement_group is not None:
                node_id = self._pg_bundle_node(opts)
            else:
                strat = opts.scheduling_strategy
                selector = dict(opts.label_selector)
                if strat is not None and hasattr(strat, "hard"):
                    selector.update(strat.hard)
                if strat is not None and hasattr(strat, "node_id"):
                    node_id = NodeID.from_hex(strat.node_id)
                    if getattr(strat, "soft", False) and (
                            node_id not in self.nodes
                            or not self.nodes[node_id].alive):
                        # soft affinity: preferred node gone — fall back to
                        # the normal pick instead of pinning to a corpse
                        node_id = self._pick_node(
                            resources, selector,
                            waiter_id=record.actor_id.hex())
                else:
                    node_id = self._pick_node(
                        resources, selector,
                        waiter_id=record.actor_id.hex())
            if node_id is None or node_id not in self.nodes or not self.nodes[node_id].alive:
                if not warned and time.monotonic() > deadline - 3590:
                    pass
                if not warned:
                    logger.warning(
                        "actor %s infeasible (resources=%s); waiting for nodes",
                        record.actor_id.hex()[:8], resources)
                    warned = True
                await asyncio.sleep(0.5)
                if time.monotonic() > deadline:
                    record.state = "DEAD"
                    record.death_cause = "scheduling timed out"
                    self._publish_actor(record)
                    return
                continue
            try:
                # optimistic view update: concurrent _schedule_actor loops
                # all read node_available, which only refreshes on 1 Hz
                # heartbeats — without this decrement a 100-actor burst
                # herds onto ONE node and the overflow parks at its raylet
                # for the whole worker_start_timeout while other nodes sit
                # empty (the next heartbeat corrects any drift)
                avail = self.node_available.get(node_id)
                if avail is not None:
                    for k, v in resources.items():
                        avail[k] = avail.get(k, 0.0) - v
                client = self.node_clients[node_id]
                reply = wire.loads(await client.call("RequestWorkerLease", wire.dumps({
                    "resources": resources,
                    "label_selector": opts.label_selector,
                    "job_id": spec.job_id,
                    "pg": (opts.placement_group.id.binary()
                           if opts.placement_group is not None else None),
                    "bundle_index": opts.placement_group_bundle_index,
                    "for_actor": record.actor_id.binary(),
                    "runtime_env": opts.runtime_env,
                }), timeout=RAY_CONFIG.worker_start_timeout_s + 30))
                if reply.get("status") != "granted":
                    await asyncio.sleep(0.2)
                    continue
                worker_addr = reply["worker_address"]
                # durably note the in-flight creation BEFORE pushing it, so a
                # GCS crash during creation can probe this worker instead of
                # scheduling a second instance (see _recover_creating_actor)
                record.address = worker_addr
                record.node_id = node_id
                record.lease_id = reply.get("lease_id", "")
                self._persist_actor(record)
                wreply = wire.loads(await self._worker_client(worker_addr).call(
                    "PushTask", wire.dumps({"spec": spec}), timeout=600.0))
                if wreply.get("status") != "ok":
                    logger.warning("actor %s creation failed on %s: %s",
                                   record.actor_id.hex()[:8], worker_addr,
                                   wreply.get("error", "")[:500])
                    record.state = "DEAD"
                    record.address = ""
                    record.node_id = None
                    record.death_cause = wreply.get("error", "creation task failed")
                    self._publish_actor(record)
                    return
                record.state = "ALIVE"
                record.address = worker_addr
                record.node_id = node_id
                self._publish_actor(record)
                return
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.warning("actor %s scheduling attempt failed: %s",
                               record.actor_id.hex()[:8], e)
                await asyncio.sleep(0.3)

    async def _recover_creating_actor(self, record: ActorRecord):
        """After an init-data replay, a PENDING_CREATION/RESTARTING record
        with an address means a creation push was in flight when we died.
        Probe the worker: if the actor is instantiated there, adopt it as
        ALIVE; otherwise release the orphaned lease and reschedule."""
        addr = record.address
        try:
            reply = wire.loads(await self._worker_client(addr).call(
                "CheckActor", wire.dumps({"actor_id": record.actor_id.binary()}),
                timeout=10.0, retries=1, connect_timeout=2.0, presend_retries=1))
            if reply.get("hosting"):
                record.state = "ALIVE"
                self._publish_actor(record)
                logger.info("actor %s adopted on %s after GCS restart",
                            record.actor_id.hex()[:8], addr)
                return
        except (RpcError, asyncio.TimeoutError, OSError) as e:
            logger.debug("actor %s adoption probe to %s failed: %s",
                         record.actor_id.hex()[:8], addr, e)
        # not there: give the lease back (if the raylet is still up), then
        # schedule from scratch
        if record.lease_id and record.node_id in self.node_clients:
            try:
                await self.node_clients[record.node_id].call(
                    "ReturnWorkerLease", wire.dumps({"lease_id": record.lease_id}),
                    timeout=5.0, retries=1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("ReturnWorkerLease for actor %s failed: %s",
                             record.actor_id.hex()[:8], e)
        record.address = ""
        record.node_id = None
        record.lease_id = ""
        self._persist_actor(record)
        spawn(self._schedule_actor(record), what="actor scheduling")

    async def _reap_job_if_driver_gone(self, job_id: JobID, job: dict):
        """Replayed RUNNING jobs lost their connection binding when the GCS
        died; poll the driver until it either reattaches (conn binding
        restored) or turns out dead (job finished + actors reaped)."""
        grace = RAY_CONFIG.gcs_driver_reattach_grace_s
        while True:
            await asyncio.sleep(grace)
            if job_id not in self.jobs or self.jobs[job_id]["state"] != "RUNNING":
                return
            if any(j == job_id for j in self.conn_jobs.values()):
                return  # driver reattached; disconnect cleanup is armed again
            addr = job.get("driver_address", "")
            if addr:
                try:
                    await self._worker_client(addr).call(
                        "Ping", b"", timeout=5.0, retries=1,
                        connect_timeout=3.0, presend_retries=1)
                    continue  # driver alive but quiet; keep polling
                except (RpcError, asyncio.TimeoutError, OSError) as e:
                    logger.debug("driver ping %s failed (job cleanup "
                                 "candidate): %s", addr, e)
            logger.warning("job %s driver gone after GCS restart; finishing it",
                           job_id.hex())
            await self._finish_job(job_id)
            return

    def _pg_bundle_node(self, opts) -> Optional[NodeID]:
        pg_id = opts.placement_group.id
        pg = self.pgs.get(pg_id)
        if pg is None or pg.state != "CREATED":
            return None
        idx = opts.placement_group_bundle_index
        if idx < 0:
            idx = 0
        return pg.bundle_nodes[idx]

    def _publish_actor(self, record: ActorRecord):
        self._persist_actor(record)
        self._publish("actors", {"event": "state", "info": record.info()})

    async def _on_actor_worker_lost(self, record: ActorRecord, reason: str):
        if record.state == "DEAD":
            return
        if record.pending_kill or (record.max_restarts != -1
                                   and record.restarts_used >= record.max_restarts):
            record.state = "DEAD"
            record.death_cause = reason
            self._publish_actor(record)
            self._record_event("actor", "ERROR", f"actor dead: {reason}",
                               actor_id=record.actor_id.hex(),
                               class_name=record.class_name)
            return
        record.restarts_used += 1
        record.state = "RESTARTING"
        self._record_event("actor", "WARNING",
                           f"actor restarting ({reason})",
                           actor_id=record.actor_id.hex(),
                           restarts_used=record.restarts_used)
        record.address = ""
        record.node_id = None
        self._publish_actor(record)
        spawn(self._schedule_actor(record), what="actor scheduling")

    async def _rpc_GetActorInfo(self, req, conn):
        record = self.actors.get(ActorID(req["actor_id"]))
        return {"info": record.info() if record else None}

    async def _rpc_WaitActorReady(self, req, conn):
        actor_id = ActorID(req["actor_id"])
        deadline = time.monotonic() + req.get("timeout", 300.0)
        while time.monotonic() < deadline:
            record = self.actors.get(actor_id)
            if record is None:
                return {"info": None}
            if record.state in ("ALIVE", "DEAD"):
                return {"info": record.info()}
            await asyncio.sleep(0.05)
        return {"info": self.actors[actor_id].info() if actor_id in self.actors else None}

    async def _rpc_GetNamedActor(self, req, conn):
        key = (req.get("namespace", "default"), req["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None or self.actors[actor_id].state == "DEAD":
            return {"info": None}
        return {"info": self.actors[actor_id].info()}

    async def _rpc_ListActors(self, req, conn):
        return {"actors": [r.info() for r in self.actors.values()]}

    async def _rpc_KillActor(self, req, conn):
        record = self.actors.get(ActorID(req["actor_id"]))
        if record is None:
            return {"status": "not_found"}
        await self._kill_actor(record, req.get("no_restart", True), "ray_tpu.kill")
        return {"status": "ok"}

    async def _kill_actor(self, record: ActorRecord, no_restart: bool, reason: str):
        if no_restart:
            record.pending_kill = True
        address = record.address
        if record.state == "ALIVE" and record.node_id in self.node_clients and address:
            try:
                # best-effort: the raylet may already be dead (node loss not
                # yet detected) — fail FAST rather than burning the default
                # connect/presend retry budget per kill (a group shutdown
                # after node loss kills many actors back-to-back)
                await self.node_clients[record.node_id].call(
                    "KillWorker", wire.dumps({"worker_address": address}),
                    timeout=10.0, retries=0, connect_timeout=2.0,
                    presend_retries=0)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("KillWorker %s on %s failed (raylet likely "
                             "dead): %s", address, record.node_id.hex()[:8], e)
        if no_restart:
            record.state = "DEAD"
            record.death_cause = reason
            if (record.namespace, record.name) in self.named_actors:
                if self.named_actors[(record.namespace, record.name)] == record.actor_id:
                    del self.named_actors[(record.namespace, record.name)]
            self._publish_actor(record)
            self._record_event("actor", "INFO", f"actor killed: {reason}",
                               actor_id=record.actor_id.hex(),
                               class_name=record.class_name)

    async def _rpc_WorkerDied(self, req, conn):
        """Raylet tells us a worker process exited (reference: raylet→GCS
        worker failure report; owners learn via the `workers` channel)."""
        address = req["worker_address"]
        self._publish("workers", {"event": "died", "worker_address": address,
                                  "node_id": req.get("node_id")})
        reason = req.get("reason", "worker died")
        self._record_event(
            "worker", "ERROR" if "OOM" in reason else "WARNING",
            f"worker died: {reason}", worker_address=address,
            node_id=req.get("node_id"))
        for record in self.actors.values():
            if record.address == address and record.state == "ALIVE":
                await self._on_actor_worker_lost(record, reason)
        return {"status": "ok"}

    # ------------------------------------------------------------------
    # placement groups (2PC reserve/commit)
    # ------------------------------------------------------------------

    async def _rpc_CreatePlacementGroup(self, req, conn):
        spec: PlacementGroupSpec = req["spec"]
        pg = PGRecord(spec)
        self.pgs[spec.pg_id] = pg
        self._persist_pg(pg)
        spawn(self._schedule_pg(pg), what="placement-group scheduling")
        return {"status": "ok"}

    async def _rpc_WaitPlacementGroupReady(self, req, conn):
        pg = self.pgs.get(PlacementGroupID(req["pg_id"]))
        if pg is None:
            return {"status": "not_found"}
        try:
            await asyncio.wait_for(pg.ready_event.wait(), req.get("timeout", 300.0))
            return {"status": "ready" if pg.state == "CREATED" else pg.state,
                    "bundle_nodes": [n.hex() if n else "" for n in pg.bundle_nodes]}
        except asyncio.TimeoutError:
            return {"status": "timeout"}

    async def _rpc_GetPlacementGroup(self, req, conn):
        pg = self.pgs.get(PlacementGroupID(req["pg_id"]))
        if pg is None:
            return {"info": None}
        return {"info": {
            "pg_id": pg.spec.pg_id.hex(),
            "state": pg.state,
            "strategy": pg.spec.strategy,
            "name": pg.spec.name,
            "bundles": [dict(b.resources) for b in pg.spec.bundles],
            "bundle_nodes": [n.hex() if n else "" for n in pg.bundle_nodes],
        }}

    async def _rpc_RemovePlacementGroup(self, req, conn):
        pg = self.pgs.get(PlacementGroupID(req["pg_id"]))
        if pg is not None:
            await self._remove_pg(pg)
        return {"status": "ok"}

    async def _remove_pg(self, pg: PGRecord):
        pg.state = "REMOVED"
        self._persist_pg(pg)
        released: set = set()
        for idx, node_id in enumerate(pg.bundle_nodes):
            if node_id is None or node_id in released \
                    or node_id not in self.node_clients:
                continue
            released.add(node_id)  # one release per node, not per bundle
            info = self.nodes.get(node_id)
            if info is not None and not info.alive:
                continue  # dead node: nothing to release
            try:
                # one retry for LIVE nodes (a swallowed transient failure
                # would leak the bundle reservation until raylet restart);
                # dead raylets still fail fast via the 2s connect bound
                await self.node_clients[node_id].call("ReleasePGBundles", wire.dumps(
                    {"pg_id": pg.spec.pg_id.binary()}), timeout=10.0,
                    retries=1, connect_timeout=2.0, presend_retries=0)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("ReleasePGBundles pg=%s to %s failed: %s",
                             pg.spec.pg_id.hex()[:8], node_id.hex()[:8], e)
        pg.ready_event.set()

    def _plan_pg(self, pg: PGRecord) -> Optional[List[NodeID]]:
        """Assign each bundle a node per strategy, against a scratch view."""
        spec = pg.spec
        scratch: Dict[NodeID, Dict[str, float]] = {
            nid: dict(self.node_available.get(nid, {}))
            for nid, info in self.nodes.items() if info.alive
        }
        assignment: List[Optional[NodeID]] = [None] * len(spec.bundles)

        def fits(nid, bundle: Bundle):
            info = self.nodes[nid]
            if bundle.label_selector and not label_match(info.labels, bundle.label_selector):
                return False
            return resources_ge(scratch[nid], bundle.resources)

        order = sorted(scratch.keys(), key=lambda n: n.hex())
        if spec.strategy in ("PACK", "STRICT_PACK"):
            # try to land everything on one node first
            for nid in order:
                trial = dict(scratch[nid])
                ok = True
                for b in spec.bundles:
                    info = self.nodes[nid]
                    if (b.label_selector and not label_match(info.labels, b.label_selector)) \
                            or not resources_ge(trial, b.resources):
                        ok = False
                        break
                    for k, v in b.resources.items():
                        trial[k] = trial.get(k, 0.0) - v
                if ok:
                    return [nid] * len(spec.bundles)
            if spec.strategy == "STRICT_PACK":
                return None
        if spec.strategy == "STRICT_SPREAD":
            used: Set[NodeID] = set()
            for i, b in enumerate(spec.bundles):
                placed = False
                for nid in order:
                    if nid in used or not fits(nid, b):
                        continue
                    assignment[i] = nid
                    used.add(nid)
                    placed = True
                    break
                if not placed:
                    return None
            return assignment  # type: ignore[return-value]
        # PACK fallback / SPREAD: greedy, SPREAD rotates through nodes
        rotation = 0
        for i, b in enumerate(spec.bundles):
            placed = False
            candidates = order[rotation:] + order[:rotation] if spec.strategy == "SPREAD" else order
            for nid in candidates:
                if fits(nid, b):
                    assignment[i] = nid
                    for k, v in b.resources.items():
                        scratch[nid][k] = scratch[nid].get(k, 0.0) - v
                    placed = True
                    if spec.strategy == "SPREAD":
                        rotation = (order.index(nid) + 1) % len(order)
                    break
            if not placed:
                return None
        return assignment  # type: ignore[return-value]

    async def _schedule_pg(self, pg: PGRecord):
        """2PC: prepare (reserve) on every node, then commit; cancel on any
        failure (reference: gcs_placement_group_scheduler.h:115-118)."""
        while pg.state in ("PENDING", "RESCHEDULING"):
            plan = self._plan_pg(pg)
            if plan is None:
                # surface each bundle to the autoscaler (PACK/SPREAD gangs
                # scale up via ordinary shape demand; STRICT_SPREAD is also
                # exported whole so distinct-node needs are visible)
                for idx, b in enumerate(pg.spec.bundles):
                    self._record_demand(
                        b.resources, b.label_selector,
                        waiter_id=f"{pg.spec.pg_id.hex()}:{idx}")
                await asyncio.sleep(0.5)
                continue
            per_node: Dict[NodeID, List[int]] = {}
            for idx, nid in enumerate(plan):
                per_node.setdefault(nid, []).append(idx)
            prepared: List[NodeID] = []
            ok = True
            for nid, idxs in per_node.items():
                try:
                    reply = wire.loads(await self.node_clients[nid].call(
                        "PreparePGBundles", wire.dumps({
                            "pg_id": pg.spec.pg_id.binary(),
                            "bundles": {i: pg.spec.bundles[i].resources for i in idxs},
                        }), timeout=10.0))
                    if reply.get("status") != "ok":
                        ok = False
                        break
                    prepared.append(nid)
                except (RpcError, asyncio.TimeoutError, OSError):
                    ok = False
                    break
            if not ok:
                # release EVERY attempted node, not just acked ones: a
                # prepare that timed out may still have applied on the
                # raylet (releasing an unprepared pg is a no-op)
                for nid in per_node:
                    try:
                        await self.node_clients[nid].call("ReleasePGBundles", wire.dumps(
                            {"pg_id": pg.spec.pg_id.binary()}), timeout=10.0, retries=1)
                    except (RpcError, asyncio.TimeoutError, OSError) as e:
                        logger.debug("ReleasePGBundles pg=%s to %s failed: %s",
                                     pg.spec.pg_id.hex()[:8], nid.hex()[:8], e)
                await asyncio.sleep(0.3)
                continue
            for nid in per_node:
                try:
                    await self.node_clients[nid].call("CommitPGBundles", wire.dumps(
                        {"pg_id": pg.spec.pg_id.binary()}), timeout=10.0)
                except (RpcError, asyncio.TimeoutError, OSError) as e:
                    logger.debug("CommitPGBundles pg=%s to %s failed: %s",
                                 pg.spec.pg_id.hex()[:8], nid.hex()[:8], e)
            pg.bundle_nodes = list(plan)
            pg.state = "CREATED"
            self._persist_pg(pg)
            pg.ready_event.set()
            self._publish("pgs", {"event": "created", "pg_id": pg.spec.pg_id.hex()})
            return

    # ------------------------------------------------------------------
    # autoscaler support (reference: gcs_autoscaler_state_manager.cc)
    # ------------------------------------------------------------------

    def _record_demand(self, resources: Dict[str, float], selector: Dict[str, str],
                       waiter_id: str = ""):
        """Count DISTINCT waiters per shape (a task retrying PickNode every
        0.5s is one unit of demand, not one per retry)."""
        now = time.monotonic()
        key = (tuple(sorted(resources.items())), tuple(sorted(selector.items())))
        entry = self.pending_demands.get(key)
        if entry is None:
            entry = self.pending_demands[key] = {
                "shape": dict(resources), "selector": dict(selector),
                "waiters": {}, "last_ts": now}
        entry["waiters"][waiter_id or "_anon"] = now
        entry["last_ts"] = now
        self._prune_demands(now)

    def _prune_demands(self, now: float):
        ttl = RAY_CONFIG.autoscaler_demand_ttl_s
        for key in [k for k, v in self.pending_demands.items()
                    if now - v["last_ts"] > ttl]:
            del self.pending_demands[key]
        for v in self.pending_demands.values():
            stale = [w for w, ts in v["waiters"].items() if now - ts > ttl]
            for w in stale:
                del v["waiters"][w]

    def _node_used(self, node_id: NodeID) -> bool:
        """A node is in use if any resource is claimed OR any lease is held
        (zero-resource actors must not look idle to the autoscaler)."""
        info = self.nodes.get(node_id)
        if info is None:
            return False
        avail = self.node_available.get(node_id)
        if avail is None:
            return True  # no view yet: err on the busy side
        if any(avail.get(k, 0.0) < v - 1e-9
               for k, v in info.total_resources.items()):
            return True
        return self.node_num_leases.get(node_id, 0) > 0

    async def _rpc_GetClusterStatus(self, req, conn):
        """Everything the autoscaler reconciler needs in one poll: per-node
        resources + idle info and the unplaceable-demand shapes."""
        now = time.monotonic()
        self._prune_demands(now)
        nodes = []
        for nid, info in self.nodes.items():
            nodes.append({
                "node_id": nid.hex(),
                "alive": info.alive,
                "is_head": info.is_head,
                "labels": dict(info.labels),
                "total": dict(info.total_resources),
                "available": dict(self.node_available.get(nid, {})),
                "used": self._node_used(nid),
                "idle_s": now - self.node_last_used.get(nid, now),
            })
        demands = [
            {"shape": v["shape"], "selector": v["selector"],
             "count": min(len(v["waiters"]), 64)}
            for v in self.pending_demands.values() if v["waiters"]
        ]
        strict_spread = [
            [dict(b.resources) for b in pg.spec.bundles]
            for pg in self.pgs.values()
            if pg.state in ("PENDING", "RESCHEDULING")
            and pg.spec.strategy == "STRICT_SPREAD"
        ]
        return {"nodes": nodes, "demands": demands, "strict_spread": strict_spread}

    # ------------------------------------------------------------------
    # debug / state api
    # ------------------------------------------------------------------

    async def _rpc_GetState(self, req, conn):
        return {
            "nodes": [n.to_dict() for n in self.nodes.values()],
            "actors": [r.info() for r in self.actors.values()],
            "jobs": list(self.jobs.values()),
            "num_objects_tracked": len(self.object_dir),
            "pgs": [
                {"pg_id": p.spec.pg_id.hex(), "state": p.state, "name": p.spec.name}
                for p in self.pgs.values()
            ],
            "uptime_s": time.time() - self.start_time,
        }


def main():
    from ray_tpu._private.common import die_with_parent

    die_with_parent()

    import argparse

    from ray_tpu._private.logs import setup_process_logging

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--address-file", required=True)
    parser.add_argument("--log-dir", default="")
    parser.add_argument("--persist-dir", default="",
                        help="durable store directory enabling GCS fault tolerance")
    args = parser.parse_args()
    setup_process_logging("gcs", args.log_dir)

    async def run():
        gcs = GcsServer(args.host, args.port, persist_dir=args.persist_dir)
        addr = await gcs.start()
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(addr)
        import os as _os

        _os.replace(tmp, args.address_file)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()

"""GCS: the cluster control plane.

Reference: ``src/ray/gcs/gcs_server.cc`` (subsystem init at :266-294) — node
membership + health (``gcs_node_manager.cc``, ``gcs_health_check_manager.cc``),
resource view (``gcs_resource_manager.cc``), actor directory + fault tolerance
(``gcs_actor_manager.h``, ``gcs_actor_scheduler.cc``), placement groups with
2PC reserve/commit (``gcs_placement_group_manager.h``,
``gcs_placement_group_scheduler.h:115-118``), job table (``gcs_job_manager.cc``),
internal KV (``gcs_kv_manager.cc``), pubsub (``src/ray/pubsub``), and a
GCS-hosted object directory (deviation: the reference resolves object
locations via owners — ``ownership_object_directory.cc``; round 1 centralizes
the directory here and owners serve small objects directly).

TPU-first: node resources carry ``TPU`` chips and slice/topology labels, and
actor/PG scheduling can select on them (slice-affine gang scheduling).
"""

from __future__ import annotations

import asyncio
import logging
import pickle
import threading

from ray_tpu._private import wire
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ray_tpu._private.common import (
    Bundle,
    NodeInfo,
    PlacementGroupSpec,
    TaskSpec,
    label_match,
    resources_ge,
)
from ray_tpu._private.config import RAY_CONFIG
from ray_tpu._private.async_util import spawn
from ray_tpu._private.task_events import RUNNING, TERMINAL_STATES
from ray_tpu._private.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu._private.rpc import RpcError, RpcServer, RetryingRpcClient, ServerConnection
from ray_tpu._private.store_client import make_store

logger = logging.getLogger("ray_tpu.gcs")


class ActorRecord:
    def __init__(self, actor_id: ActorID, spec: TaskSpec):
        self.actor_id = actor_id
        self.spec = spec
        opts = spec.actor_options
        self.name = opts.name or ""
        self.namespace = opts.namespace or "default"
        self.lifetime = opts.lifetime
        self.max_restarts = opts.max_restarts
        self.restarts_used = 0
        self.state = "PENDING_CREATION"
        self.address = ""
        self.node_id: Optional[NodeID] = None
        self.job_id = spec.job_id
        self.death_cause = ""
        self.class_name = ""
        self.pending_kill = False
        self.lease_id = ""

    def dump(self) -> dict:
        """Durable form for the store client (replayed on GCS restart)."""
        return {
            "spec": self.spec,
            "state": self.state,
            "address": self.address,
            "node_id": self.node_id.binary() if self.node_id else None,
            "restarts_used": self.restarts_used,
            "death_cause": self.death_cause,
            "class_name": self.class_name,
            "pending_kill": self.pending_kill,
            "lease_id": self.lease_id,
        }

    @classmethod
    def restore(cls, data: dict) -> "ActorRecord":
        spec: TaskSpec = data["spec"]
        record = cls(spec.actor_id, spec)
        record.state = data["state"]
        record.address = data["address"]
        record.node_id = NodeID(data["node_id"]) if data["node_id"] else None
        record.restarts_used = data["restarts_used"]
        record.death_cause = data["death_cause"]
        record.class_name = data["class_name"]
        record.pending_kill = data["pending_kill"]
        record.lease_id = data.get("lease_id", "")
        return record

    def info(self) -> dict:
        return {
            "actor_id": self.actor_id.hex(),
            "state": self.state,
            "address": self.address,
            "node_id": self.node_id.hex() if self.node_id else "",
            "name": self.name,
            "namespace": self.namespace,
            "restarts_used": self.restarts_used,
            "max_restarts": self.max_restarts,
            "death_cause": self.death_cause,
            "class_name": self.class_name,
            "job_id": self.job_id.hex(),
            "lifetime": self.lifetime,
        }


class PGRecord:
    def __init__(self, spec: PlacementGroupSpec):
        self.spec = spec
        self.state = "PENDING"  # PENDING | CREATED | REMOVED | RESCHEDULING
        self.bundle_nodes: List[Optional[NodeID]] = [None] * len(spec.bundles)
        self.ready_event = asyncio.Event()

    def dump(self) -> dict:
        return {
            "spec": self.spec,
            "state": self.state,
            "bundle_nodes": [n.binary() if n else None for n in self.bundle_nodes],
        }

    @classmethod
    def restore(cls, data: dict) -> "PGRecord":
        pg = cls(data["spec"])
        pg.state = data["state"]
        pg.bundle_nodes = [NodeID(b) if b else None for b in data["bundle_nodes"]]
        if pg.state in ("CREATED", "REMOVED"):
            pg.ready_event.set()
        return pg


class GcsTaskManager:
    """Bounded per-job store of task lifecycle events.

    Reference: ``gcs/gcs_server/gcs_task_manager.cc`` — core workers flush
    batched state transitions here; the store keeps a bounded per-job ring
    (drop-oldest + a drop counter so truncation is visible, mirroring
    ``RAY_task_events_max_num_task_in_gcs``), merges owner-side and
    executor-side events by task id, and serves ``ray list tasks`` /
    ``ray summary tasks`` / the dashboard timeline."""

    def __init__(self, max_per_job: Optional[int] = None,
                 max_events_per_task: Optional[int] = None):
        self.max_per_job = max_per_job or RAY_CONFIG.gcs_task_events_max_per_job
        self.max_events_per_task = (max_events_per_task
                                    or RAY_CONFIG.task_events_max_per_task)
        # job_hex -> {task_id_hex: record}, insertion-ordered (dict) so the
        # oldest task evicts first when the ring is full
        self.jobs: Dict[str, Dict[str, dict]] = {}
        # flat id index: owner and executor flush independently (the
        # executor's RUNNING may even arrive first), and the lookup runs
        # once per event — it must be O(1), not a scan over every ring
        self._by_tid: Dict[str, dict] = {}
        self.dropped: Dict[str, int] = {}  # per-job: ring evictions +
        #                                    reporter-side buffer drops

    def add_events(self, events: List[dict], dropped: int = 0):
        for ev in events:
            tid = ev.get("task_id")
            if not tid:
                continue
            rec = self._by_tid.get(tid)
            if rec is None:
                job = ev.get("job_id") or "unknown"
                ring = self.jobs.setdefault(job, {})
                while len(ring) >= self.max_per_job:
                    oldest = next(iter(ring))
                    del ring[oldest]
                    self._by_tid.pop(oldest, None)
                    self.dropped[job] = self.dropped.get(job, 0) + 1
                rec = ring[tid] = self._by_tid[tid] = {
                    "task_id": tid, "job_id": job, "name": "", "state": "",
                    "attempt": 0, "error": "", "worker": "", "node": "",
                    "arg_bytes": 0, "ret_bytes": 0,
                    "span_id": "", "parent_span": "",
                    "events": [], "_last_ts": 0.0,
                }
            self._merge(rec, ev)
        if dropped:
            self.dropped["_reporter"] = self.dropped.get("_reporter", 0) + dropped

    def _find(self, tid: str) -> Optional[dict]:
        return self._by_tid.get(tid)

    def _merge(self, rec: dict, ev: dict):
        entry = {"state": ev["state"], "ts": ev["ts"],
                 "attempt": ev.get("attempt", 0)}
        if ev.get("error"):
            entry["error"] = ev["error"]
        events = rec["events"]
        events.append(entry)
        if len(events) > self.max_events_per_task:
            del events[: len(events) - self.max_events_per_task]
        if ev.get("name"):
            rec["name"] = ev["name"]
        # causal linkage for the timeline: the task's deterministic
        # execution-span id and the submitter's active span (latest
        # non-empty wins, so a retry's span supersedes attempt 0's)
        if ev.get("span_id"):
            rec["span_id"] = ev["span_id"]
        if ev.get("parent_span"):
            rec["parent_span"] = ev["parent_span"]
        if ev.get("worker"):
            rec["worker"] = ev["worker"]
        if ev.get("node"):
            rec["node"] = ev["node"]
        if ev.get("error"):
            rec["error"] = ev["error"]
        # object-size accounting: arg bytes ride SUBMITTED, return bytes
        # the terminal event; max() keeps the merge idempotent under
        # replays and retry re-submissions report their largest attempt
        if ev.get("arg_bytes"):
            rec["arg_bytes"] = max(rec["arg_bytes"], int(ev["arg_bytes"]))
        if ev.get("ret_bytes"):
            rec["ret_bytes"] = max(rec["ret_bytes"], int(ev["ret_bytes"]))
        rec["attempt"] = max(rec["attempt"], ev.get("attempt", 0))
        # latest-state resolution: owner and executor flush independently,
        # so events can arrive out of ts order; a terminal state is never
        # overridden by a late RUNNING
        if ev["state"] in TERMINAL_STATES or (
                rec["state"] not in TERMINAL_STATES
                and ev["ts"] >= rec["_last_ts"]):
            rec["state"] = ev["state"]
        rec["_last_ts"] = max(rec["_last_ts"], ev["ts"])

    @staticmethod
    def _dump(rec: dict) -> dict:
        events = sorted(rec["events"], key=lambda e: e["ts"])
        out = {k: v for k, v in rec.items() if not k.startswith("_")}
        out["events"] = events
        if events:
            out["start_ts"] = events[0]["ts"]
            out["end_ts"] = events[-1]["ts"]
            out["duration_s"] = events[-1]["ts"] - events[0]["ts"]
        return out

    def list_tasks(self, job_id: Optional[str] = None,
                   name: Optional[str] = None, state: Optional[str] = None,
                   limit: int = 200) -> List[dict]:
        out = []
        for job, ring in self.jobs.items():
            if job_id and job != job_id:
                continue
            for rec in ring.values():
                # substring match: function names are qualnames
                # ("mod.<locals>.fn"), exact equality would be unusable
                if name and name not in rec["name"]:
                    continue
                if state and rec["state"] != state:
                    continue
                out.append(self._dump(rec))
        out.sort(key=lambda r: r.get("start_ts", 0.0))
        return out[-int(limit):]

    def get_task(self, tid: str) -> Optional[dict]:
        rec = self._find(tid)
        return self._dump(rec) if rec is not None else None

    def summarize(self, job_id: Optional[str] = None) -> dict:
        """Per-function counts by lifecycle state (the ``ray summary
        tasks`` analog), plus per-function object-size accounting
        (summed serialized argument / returned-object bytes)."""
        per_fn: Dict[str, Dict[str, int]] = {}
        sizes: Dict[str, Dict[str, int]] = {}
        total = 0
        for job, ring in self.jobs.items():
            if job_id and job != job_id:
                continue
            for rec in ring.values():
                total += 1
                fn = rec["name"] or "<unknown>"
                by_state = per_fn.setdefault(fn, {})
                st = rec["state"] or "UNKNOWN"
                by_state[st] = by_state.get(st, 0) + 1
                sz = sizes.setdefault(fn, {"arg_bytes": 0, "ret_bytes": 0})
                sz["arg_bytes"] += rec.get("arg_bytes", 0)
                sz["ret_bytes"] += rec.get("ret_bytes", 0)
        return {"per_function": per_fn, "per_function_bytes": sizes,
                "total": total, "dropped": dict(self.dropped)}


class ShardedTaskEvents:
    """Sharded + pipelined front for ``GcsTaskManager``, with the merge
    work OFF the GCS event loop.

    5k+ tasks/s of lifecycle events must not serialize on one merge path:
    ``AddTaskEvents`` routes each event by task-id hash into one of
    ``gcs_task_event_shards`` bounded ingest queues and returns immediately.
    A dedicated merge THREAD (not an event-loop task — merging 20k queued
    events inline used to stall heartbeats and lease grants for the whole
    batch) owns the shard stores exclusively: it drains the queues, and
    read RPCs hand their query over as a closure (:meth:`read`) that the
    thread executes against its stores after everything already queued has
    merged. The handoff is lock-free — single-owner stores, thread-safe
    deques for the queues and the read requests, results resolved back
    onto the event loop via ``call_soon_threadsafe`` — so ``ListTasks`` /
    timeline scrapes never block ingest and ingest never blocks the loop.
    Per-shard rings keep the global per-job bound at
    ``gcs_task_events_max_per_job`` in aggregate."""

    def __init__(self, nshards: Optional[int] = None):
        n = max(1, nshards or RAY_CONFIG.gcs_task_event_shards)
        per_shard_cap = max(1, RAY_CONFIG.gcs_task_events_max_per_job // n)
        self.shards = [GcsTaskManager(max_per_job=per_shard_cap)
                       for _ in range(n)]
        # deque append/popleft are GIL-atomic: the event loop enqueues,
        # the merge thread dequeues, no lock needed
        self._queues: List[deque] = [deque() for _ in range(n)]
        self._reporter_drops: deque = deque()  # reporter-side drop counts
        self._reads: deque = deque()  # (closure, loop|None, future|Event)
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._thread_lock = threading.Lock()
        self._stopped = False
        self._qmax = max(256, RAY_CONFIG.gcs_task_event_ingest_max)
        self.ingest_dropped = 0  # queue-full drops (visible in summarize)
        self.batches = 0  # drained merge batches (pipelining evidence)

    def _shard_of(self, tid: str) -> int:
        # task ids are hex; the tail bytes are well distributed
        try:
            return int(tid[-4:], 16) % len(self.shards)
        except (ValueError, TypeError):
            return 0

    def ingest(self, events: List[dict], dropped: int = 0):
        """Handler-side: route + enqueue, no merging on the RPC path."""
        for ev in events:
            tid = ev.get("task_id")
            if not tid:
                continue
            q = self._queues[self._shard_of(tid)]
            if len(q) >= self._qmax:
                # drop-OLDEST, matching the store rings: the newest events
                # carry the terminal FINISHED/FAILED transitions that must
                # win the merge — shedding them would freeze tasks at
                # RUNNING forever in every surface
                q.popleft()
                self.ingest_dropped += 1
            q.append(ev)
        if dropped:
            self._reporter_drops.append(int(dropped))
        if events or dropped:
            self._ensure_thread()
            self._wake.set()

    # -- merge thread ---------------------------------------------------

    def _ensure_thread(self):
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._thread_lock:
            if self._thread is None or not self._thread.is_alive():
                self._stopped = False
                self._thread = threading.Thread(
                    target=self._merge_loop, name="gcs-task-event-merge",
                    daemon=True)
                self._thread.start()

    def stop(self):
        self._stopped = True
        self._wake.set()

    def _merge_loop(self):
        while True:
            self._wake.wait(timeout=0.5)
            # raylint: disable=RCE002 _wake is a threading.Event — itself the synchronization primitive; .clear() is misread as a container mutation, and a lost wakeup is bounded by the 0.5s poll
            self._wake.clear()
            try:
                self._drain_queues()
            except Exception:
                logger.exception("task-event merge iteration failed")
            self._serve_reads()
            if self._stopped:
                self._serve_reads()  # don't strand a late read forever
                return

    def _drain_queues(self):
        for i, q in enumerate(self._queues):
            while q:
                batch = []
                while q and len(batch) < 1024:
                    batch.append(q.popleft())
                self.shards[i].add_events(batch)
                # raylint: disable=RCE001 _drain_queues runs inline on a caller only when the merge thread is not alive (flush_sync checks); live-thread callers hand off through _reads instead, so two contexts never drain concurrently
                self.batches += 1
        while self._reporter_drops:
            self.shards[0].add_events([], self._reporter_drops.popleft())

    def _serve_reads(self):
        while self._reads:
            try:
                # read-your-writes: events enqueued BEFORE this read was
                # posted must be merged before it runs
                self._drain_queues()
            except Exception:
                logger.exception("task-event merge before read failed")
            fn, loop, fut = self._reads.popleft()
            try:
                result, err = fn(self), None
            except BaseException as e:
                result, err = None, e
            if loop is None:  # sync barrier (threading.Event)
                fut.set()
                continue

            def _resolve(fut=fut, result=result, err=err):
                if fut.cancelled():
                    return
                if err is not None:
                    fut.set_exception(err)
                else:
                    fut.set_result(result)

            try:
                loop.call_soon_threadsafe(_resolve)
            except RuntimeError as e:  # loop already closed (shutdown race)
                logger.debug("task-event read resolve dropped: %s", e)

    async def read(self, fn: Callable[["ShardedTaskEvents"], Any]):
        """Run ``fn(self)`` on the merge thread, after everything already
        enqueued has merged (read-your-writes), and await the result
        WITHOUT blocking the caller's event loop — heartbeats and ingest
        proceed while the merge thread works."""
        self._ensure_thread()
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._reads.append((fn, loop, fut))
        self._wake.set()
        return await fut

    def flush_sync(self, max_events: int = 0):
        """Synchronous read barrier for callers OUTSIDE the GCS event loop
        (tests, tools): returns once everything currently queued has
        merged. With no merge thread running (directly-constructed stores
        in unit tests) the merge runs inline on the caller."""
        t = self._thread
        if t is None or not t.is_alive():
            self._drain_queues()
            return
        done = threading.Event()
        self._reads.append((lambda _tm: None, None, done))
        self._wake.set()
        done.wait(timeout=30.0)

    # -- reads fan out over the shards (call via read()/flush_sync) -----

    def add_events(self, events: List[dict], dropped: int = 0):
        """Synchronous compatibility path: enqueue + barrier (the shard
        stores belong to the merge thread; writing them directly from the
        caller would race it)."""
        self.ingest(events, dropped)
        self.flush_sync()

    def list_tasks(self, job_id=None, name=None, state=None,
                   limit: int = 200) -> List[dict]:
        out = []
        for shard in self.shards:
            out.extend(shard.list_tasks(job_id=job_id, name=name,
                                        state=state, limit=limit))
        out.sort(key=lambda r: r.get("start_ts", 0.0))
        return out[-int(limit):]

    def get_task(self, tid: str) -> Optional[dict]:
        return self.shards[self._shard_of(tid)].get_task(tid)

    def summarize(self, job_id=None) -> dict:
        per_fn: Dict[str, Dict[str, int]] = {}
        sizes: Dict[str, Dict[str, int]] = {}
        dropped: Dict[str, int] = {}
        total = 0
        for shard in self.shards:
            s = shard.summarize(job_id=job_id)
            total += s["total"]
            for fn, by_state in s["per_function"].items():
                agg = per_fn.setdefault(fn, {})
                for st, n in by_state.items():
                    agg[st] = agg.get(st, 0) + n
            for fn, sz in s["per_function_bytes"].items():
                agg_sz = sizes.setdefault(fn, {"arg_bytes": 0, "ret_bytes": 0})
                agg_sz["arg_bytes"] += sz["arg_bytes"]
                agg_sz["ret_bytes"] += sz["ret_bytes"]
            for k, v in s["dropped"].items():
                dropped[k] = dropped.get(k, 0) + v
        if self.ingest_dropped:
            dropped["_ingest_queue"] = self.ingest_dropped
        return {"per_function": per_fn, "per_function_bytes": sizes,
                "total": total, "dropped": dropped,
                "shards": len(self.shards), "merge_batches": self.batches}


class MetricsHistory:
    """Bounded two-tier time-series ring over the cluster's metric
    snapshots.

    The GCS already receives every process's registry snapshot (the
    core-worker/raylet auto-flush KV puts into ns ``metrics``); before
    this class, ``/metrics`` could only serve the LATEST values. Here the
    latest per-process payloads are aggregated cluster-wide on a sampling
    cadence into a raw ring (``metrics_history_interval_s``, default 5 s)
    and periodically rolled up into a coarser ring
    (``metrics_history_rollup_s``, default 60 s: avg/min/max for gauges,
    cumulative-last + rate for counters and histograms — histogram samples
    keep the full bucket vector so percentiles-over-time come from bucket
    deltas). Surfaced via the ``GetMetricsHistory`` RPC,
    ``util.state.metrics_history`` and ``GET /api/metrics/history``."""

    STALE_S = 120.0  # ignore process snapshots older than this

    def __init__(self, raw_interval_s: Optional[float] = None,
                 raw_points: Optional[int] = None,
                 rollup_interval_s: Optional[float] = None,
                 rollup_points: Optional[int] = None):
        self.raw_interval_s = (raw_interval_s
                               or RAY_CONFIG.metrics_history_interval_s)
        self.raw_points = raw_points or RAY_CONFIG.metrics_history_raw_points
        self.rollup_interval_s = (rollup_interval_s
                                  or RAY_CONFIG.metrics_history_rollup_s)
        self.rollup_points = (rollup_points
                              or RAY_CONFIG.metrics_history_rollup_points)
        self._procs: Dict[str, dict] = {}  # kv key -> latest proc payload
        self._raw: Dict[str, deque] = {}
        self._rollup: Dict[str, deque] = {}
        self._kinds: Dict[str, str] = {}
        self._last_rollup = 0.0
        self.samples = 0

    # -- ingestion ------------------------------------------------------

    def observe_payload(self, key: str, payload: dict):
        """Feed one process's registry snapshot (called on every KV put
        into the ``metrics`` namespace — no new reporting path)."""
        if isinstance(payload, dict) and "metrics" in payload:
            self._procs[key] = payload

    def _fresh_procs(self, now: float) -> List[dict]:
        stale = [k for k, p in self._procs.items()
                 if now - p.get("time", 0) > self.STALE_S]
        for k in stale:
            del self._procs[k]
        return list(self._procs.values())

    def latest_by_node(self, name: str) -> Dict[str, float]:
        """Latest per-node value of a gauge (max across a node's processes
        and tag sets) — the health monitor's straggler-outlier view."""
        out: Dict[str, float] = {}
        now = time.time()
        for p in self._fresh_procs(now):
            m = p.get("metrics", {}).get(name)
            if not m or m.get("kind") != "gauge":
                continue
            vals = [v for v in m.get("data", {}).values()
                    if isinstance(v, (int, float))]
            if not vals:
                continue
            node = str(p.get("node", ""))[:16]
            out[node] = max(out.get(node, float("-inf")), max(vals))
        return out

    # -- sampling -------------------------------------------------------

    def _aggregate(self, now: float) -> Dict[str, dict]:
        """Cluster-wide aggregate per metric name across all fresh process
        snapshots and tag sets: counters sum; gauges sum + max + process
        count; histograms sum counts/sums and element-wise bucket rows."""
        agg: Dict[str, dict] = {}
        for p in self._fresh_procs(now):
            for name, m in p.get("metrics", {}).items():
                kind = m.get("kind")
                data = m.get("data", {})
                self._kinds[name] = kind
                if kind == "counter":
                    s = agg.setdefault(name, {"value": 0.0})
                    s["value"] += sum(v for v in data.values()
                                      if isinstance(v, (int, float)))
                elif kind == "gauge":
                    vals = [v for v in data.values()
                            if isinstance(v, (int, float))]
                    if not vals:
                        continue
                    s = agg.setdefault(
                        name, {"value": 0.0, "max": float("-inf"), "n": 0})
                    s["value"] += sum(vals)
                    s["max"] = max(s["max"], max(vals))
                    s["n"] += 1
                elif kind == "histogram":
                    bounds = list(data.get("boundaries") or [])
                    s = agg.setdefault(name, {
                        "count": 0, "sum": 0.0,
                        "buckets": [0] * (len(bounds) + 1),
                        "boundaries": bounds})
                    for counts in data.get("counts", {}).values():
                        s["count"] += sum(counts)
                        if len(counts) == len(s["buckets"]):
                            for i, c in enumerate(counts):
                                s["buckets"][i] += c
                    s["sum"] += sum(v for v in data.get("sums", {}).values()
                                    if isinstance(v, (int, float)))
        return agg

    def sample(self, now: Optional[float] = None):
        """Append one raw-tier point per metric (called every
        ``raw_interval_s`` by the GCS sampling loop), rolling the coarse
        tier up when its interval has elapsed."""
        now = time.time() if now is None else now
        self.samples += 1
        for name, s in self._aggregate(now).items():
            ring = self._raw.get(name)
            if ring is None:
                ring = self._raw[name] = deque(maxlen=self.raw_points)
            ring.append({"ts": now, **s})
        if now - self._last_rollup >= self.rollup_interval_s:
            self._last_rollup = now
            self._roll(now)

    def _roll(self, now: float):
        for name, ring in self._raw.items():
            window = [p for p in ring
                      if p["ts"] > now - self.rollup_interval_s]
            if not window:
                continue
            kind = self._kinds.get(name, "gauge")
            first, last = window[0], window[-1]
            span = max(last["ts"] - first["ts"], 1e-9)
            point: Dict[str, Any] = {"ts": now, "n_raw": len(window)}
            if kind == "gauge":
                # avg/min/max of the cluster-summed series (raw samples'
                # per-process "max" is a different axis — mixing it in
                # would let max < value on multi-process gauges)
                vals = [p["value"] for p in window]
                point["value"] = sum(vals) / len(vals)
                point["min"] = min(vals)
                point["max"] = max(vals)
            elif kind == "counter":
                point["value"] = last["value"]
                # clamped at 0: the cluster value is a sum over the CURRENT
                # membership, so a process exiting (or stale-pruned) drops
                # its lifetime total from the series — that step down is a
                # membership change, not negative throughput
                point["rate"] = (max(0.0, last["value"] - first["value"])
                                 / span if len(window) > 1 else 0.0)
            else:  # histogram: cumulative last + observation rate
                point["count"] = last["count"]
                point["sum"] = last["sum"]
                point["buckets"] = list(last.get("buckets") or ())
                point["boundaries"] = list(last.get("boundaries") or ())
                point["rate"] = (max(0.0, last["count"] - first["count"])
                                 / span if len(window) > 1 else 0.0)
            ring2 = self._rollup.get(name)
            if ring2 is None:
                ring2 = self._rollup[name] = deque(maxlen=self.rollup_points)
            ring2.append(point)

    # -- reads ----------------------------------------------------------

    def names(self) -> List[str]:
        return sorted(self._raw.keys())

    def series(self, name: str, window_s: Optional[float] = None,
               tier: str = "auto", now: Optional[float] = None) -> dict:
        """One metric's time series. ``tier="auto"`` picks raw while the
        requested window still fits in the raw ring, else rollup."""
        now = time.time() if now is None else now
        if tier not in ("raw", "rollup", "auto"):
            tier = "auto"
        if tier == "auto":
            raw_span = self.raw_interval_s * self.raw_points
            tier = ("raw" if window_s is None or window_s <= raw_span
                    else "rollup")
        ring = (self._raw if tier == "raw" else self._rollup).get(name)
        points = list(ring) if ring else []
        if window_s:
            cutoff = now - window_s
            points = [p for p in points if p["ts"] >= cutoff]
        return {"name": name, "kind": self._kinds.get(name, ""),
                "tier": tier,
                "interval_s": (self.raw_interval_s if tier == "raw"
                               else self.rollup_interval_s),
                "points": points}


class GoodputLedger:
    """GCS-side per-job goodput aggregation (``util/goodput.py`` is the
    process-side half).

    Every process with an active ledger flushes a CUMULATIVE payload —
    bucket seconds, counters, wall time — into KV ns ``goodput`` on the
    metrics cadence; the same ``_observe_kv`` tap that feeds
    ``MetricsHistory`` lands them here. A job's view sums the latest
    payload of every process tagged with it, deriving
    ``goodput_fraction`` (step_compute share of summed wall). Finished
    jobs keep their final ledgers (bounded LRU) so ``/api/goodput`` can
    still explain a completed run; the health scanner's
    :meth:`findings` pass also maintains the per-job trailing windows
    behind the recompile-storm and goodput-regression findings."""

    STALE_S = 120.0       # a proc not flushing for this long is not fresh
    MAX_JOBS = 64         # finished-job LRU bound
    HISTORY_POINTS = 240  # per-job trailing-window ring (scan cadence)

    def __init__(self):
        # job -> proc kv-key -> latest cumulative payload
        self._jobs: Dict[str, Dict[str, dict]] = {}
        self._fraction_hist: Dict[str, deque] = {}
        self._recompile_hist: Dict[str, deque] = {}

    # -- ingestion ------------------------------------------------------

    def observe(self, key: str, payload: dict):
        if not isinstance(payload, dict) or "buckets" not in payload:
            return
        job = str(payload.get("job") or "") or "(untagged)"
        # a process belongs to one job at a time: a re-tagged worker's
        # old entry must not keep inflating the previous job
        for j, procs in self._jobs.items():
            if j != job:
                procs.pop(key, None)
        procs = self._jobs.pop(job, {})
        self._jobs[job] = procs  # move-to-end: dict order is the LRU
        procs[key] = payload
        while len(self._jobs) > self.MAX_JOBS:
            evicted = next(iter(self._jobs))
            del self._jobs[evicted]
            self._fraction_hist.pop(evicted, None)
            self._recompile_hist.pop(evicted, None)

    # -- reads ----------------------------------------------------------

    def _job_view(self, job: str, procs: Dict[str, dict],
                  now: float) -> dict:
        buckets: Dict[str, float] = {}
        counters: Dict[str, float] = {}
        wall = 0.0
        mfu = None
        nodes = set()
        fresh = 0
        last_update = 0.0
        for p in procs.values():
            for b, v in (p.get("buckets") or {}).items():
                if isinstance(v, (int, float)):
                    buckets[b] = buckets.get(b, 0.0) + float(v)
            for c, v in (p.get("counters") or {}).items():
                if isinstance(v, (int, float)):
                    counters[c] = counters.get(c, 0) + v
            wall += float(p.get("wall_s") or 0.0)
            if isinstance(p.get("mfu"), (int, float)):
                mfu = max(mfu if mfu is not None else 0.0, float(p["mfu"]))
            if p.get("node"):
                nodes.add(str(p["node"])[:16])
            ts = float(p.get("time") or 0.0)
            last_update = max(last_update, ts)
            if now - ts <= self.STALE_S:
                fresh += 1
        view = {
            "job": job, "wall_s": wall, "buckets": buckets,
            "counters": counters,
            "goodput_fraction": (buckets.get("step_compute", 0.0) / wall
                                 if wall > 0 else 0.0),
            "procs": len(procs), "fresh_procs": fresh,
            "nodes": sorted(nodes), "last_update": last_update,
        }
        if mfu is not None:
            view["mfu"] = mfu
        return view

    def jobs(self, now: Optional[float] = None) -> Dict[str, dict]:
        now = time.time() if now is None else now
        return {job: self._job_view(job, procs, now)
                for job, procs in self._jobs.items() if procs}

    # -- health findings ------------------------------------------------

    def findings(self, now: float, cfg) -> List[dict]:
        """One health-scan pass over every job with fresh reporters:
        recompile storms (recompile count within the trailing window),
        input-bound jobs (input_stall share of wall), checkpoint pauses
        over budget (mean pause per save), and goodput regression vs
        the job's OWN trailing-window mean. Also appends this scan's
        point to the per-job trailing rings."""
        out: List[dict] = []
        for job, view in self.jobs(now).items():
            if view["fresh_procs"] == 0:
                continue  # finished/stale job: freeze, never re-warn
            wall = view["wall_s"]
            buckets = view["buckets"]
            counters = view["counters"]
            fraction = view["goodput_fraction"]
            rc_hist = self._recompile_hist.setdefault(
                job, deque(maxlen=self.HISTORY_POINTS))
            fr_hist = self._fraction_hist.setdefault(
                job, deque(maxlen=self.HISTORY_POINTS))
            if wall >= cfg.goodput_min_wall_s:
                # recompile storm: recompiles accumulated inside the
                # window (vs the oldest in-window history point; with no
                # history yet the lifetime total is the window)
                recompiles = counters.get("recompiles", 0)
                cutoff = now - cfg.goodput_recompile_window_s
                base = next((v for ts, v in rc_hist if ts >= cutoff), None)
                recent = recompiles - base if base is not None else recompiles
                if recent >= cfg.goodput_recompile_storm_n:
                    out.append({
                        "kind": "recompile_storm", "severity": "warning",
                        "job": job, "recompiles_in_window": recent,
                        "window_s": cfg.goodput_recompile_window_s,
                        "compiles_total": counters.get("compiles", 0),
                        "compile_s": buckets.get("compile", 0.0)})
                stall_frac = buckets.get("input_stall", 0.0) / wall
                if stall_frac > cfg.goodput_input_bound_frac:
                    out.append({
                        "kind": "input_bound", "severity": "warning",
                        "job": job, "input_stall_fraction": stall_frac,
                        "threshold": cfg.goodput_input_bound_frac,
                        "input_stall_s": buckets.get("input_stall", 0.0)})
                saves = counters.get("ckpt_saves", 0)
                pause = buckets.get("ckpt_pause", 0.0)
                if saves > 0 and pause / saves > cfg.goodput_ckpt_budget_s:
                    out.append({
                        "kind": "ckpt_pause_over_budget",
                        "severity": "warning", "job": job,
                        "mean_pause_s": pause / saves, "saves": saves,
                        "budget_s": cfg.goodput_ckpt_budget_s})
                if len(fr_hist) >= cfg.goodput_regression_min_points:
                    trailing = sum(v for _, v in fr_hist) / len(fr_hist)
                    if trailing - fraction > cfg.goodput_regression_drop:
                        out.append({
                            "kind": "goodput_regression",
                            "severity": "warning", "job": job,
                            "goodput_fraction": fraction,
                            "trailing_mean": trailing,
                            "drop": trailing - fraction,
                            "threshold": cfg.goodput_regression_drop})
            rc_hist.append((now, counters.get("recompiles", 0)))
            fr_hist.append((now, fraction))
        return out


def build_timeline(records: List[dict], spans: Optional[List[dict]] = None,
                   start_ts: Optional[float] = None,
                   end_ts: Optional[float] = None) -> dict:
    """Render merged task-event records (+ optional span records) as a
    Perfetto-loadable chrome-trace JSON object.

    Tracks: one synthetic pid per node, one tid per worker (named via
    ``ph:"M"`` metadata). Each task renders as a ``pending:`` slice
    (SUBMITTED→RUNNING — scheduling latency is visible, not hidden) and an
    execution slice (RUNNING→terminal); parent→child task edges join on
    the span linkage the task events carry (``span_id``/``parent_span``)
    and render as the PR 3 flow arrows (``ph:"s"/"f"`` pairs). Span
    records (``tracing.profile()`` blocks, submit anchors) are appended
    through :func:`tracing.spans_to_chrome_events` so the built-in
    hot-path spans appear in the same trace."""
    events: List[dict] = []
    pids: Dict[str, int] = {}
    tids: Dict[Tuple[int, str], int] = {}

    def _pid(node: str) -> int:
        if node not in pids:
            pids[node] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[node], "tid": 0,
                           "args": {"name": f"node:{node[:12] or '?'}"}})
        return pids[node]

    def _tid(pid: int, worker: str) -> int:
        key = (pid, worker)
        if key not in tids:
            tids[key] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tids[key],
                           "args": {"name": f"worker:{worker or '?'}"}})
        return tids[key]

    slices: Dict[str, Tuple[int, int, float, float]] = {}
    kept: List[dict] = []
    for rec in records:
        evs = rec.get("events") or []
        if not evs:
            continue
        t0, t1 = rec.get("start_ts", evs[0]["ts"]), rec.get(
            "end_ts", evs[-1]["ts"])
        if start_ts is not None and t1 < start_ts:
            continue
        if end_ts is not None and t0 > end_ts:
            continue
        kept.append(rec)
        pid = _pid(rec.get("node", ""))
        tid = _tid(pid, rec.get("worker", ""))
        name = rec.get("name") or rec["task_id"][:12]
        run_ts = next((e["ts"] for e in evs if e["state"] == RUNNING), None)
        if run_ts is not None and run_ts > t0:
            events.append({
                "name": f"pending:{name}", "cat": "pending", "ph": "X",
                "ts": t0 * 1e6, "dur": (run_ts - t0) * 1e6,
                "pid": pid, "tid": tid,
                "args": {"task_id": rec["task_id"]}})
        exec_start = run_ts if run_ts is not None else t0
        events.append({
            "name": name, "cat": "task", "ph": "X",
            "ts": exec_start * 1e6,
            "dur": max(t1 - exec_start, 0.0) * 1e6,
            "pid": pid, "tid": tid,
            "args": {"task_id": rec["task_id"], "state": rec.get("state"),
                     "attempt": rec.get("attempt", 0),
                     "job_id": rec.get("job_id", "")}})
        if rec.get("span_id"):
            slices[rec["span_id"]] = (pid, tid, exec_start,
                                      max(t1 - exec_start, 0.0))
    flow_n = 0
    for rec in kept:
        parent = slices.get(rec.get("parent_span") or "")
        child = slices.get(rec.get("span_id") or "")
        if parent is None or child is None or parent is child:
            continue
        flow_n += 1
        ppid, ptid, pts, pdur = parent
        cpid, ctid, cts, _ = child
        # bind the arrow start inside the parent slice
        anchor = min(max(cts, pts), pts + pdur)
        events.append({"name": "task_flow", "cat": "flow", "ph": "s",
                       "id": flow_n, "ts": anchor * 1e6,
                       "pid": ppid, "tid": ptid})
        events.append({"name": "task_flow", "cat": "flow", "ph": "f",
                       "bp": "e", "id": flow_n, "ts": cts * 1e6,
                       "pid": cpid, "tid": ctid})
    if spans:
        from ray_tpu.util.tracing import spans_to_chrome_events

        window = [s for s in spans
                  if (start_ts is None or s["ts"] + max(s.get("dur", 0.0), 0.0)
                      >= start_ts)
                  and (end_ts is None or s["ts"] <= end_ts)]
        # span flow ids live in their own range so they never collide with
        # the task-record arrows above
        events.extend(spans_to_chrome_events(window,
                                             flow_id_base=flow_n + 1_000_000))
    return {"traceEvents": events}


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, persist_dir: str = ""):
        self.store = make_store(persist_dir)
        self.server = RpcServer(self._handle, host, port)
        self.server.on_disconnect = self._on_disconnect
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.node_available: Dict[NodeID, Dict[str, float]] = {}
        # last availability broadcast per node (delta suppression for the
        # resource_view syncer stream; reference: ray_syncer.h:89), plus
        # the per-tick coalescing set: availability changes mark a node
        # dirty and ONE batched resource_view publish per GCS tick carries
        # the latest view of every dirty node — a 20k-task burst flapping
        # availability 50×/s per node costs one publish per tick, not one
        # per change (reference: the ray_syncer broadcast interval)
        self._last_view_pub: Dict[NodeID, Dict[str, float]] = {}
        self._view_dirty: Set[NodeID] = set()
        self.node_last_seen: Dict[NodeID, float] = {}
        self.node_clients: Dict[NodeID, RetryingRpcClient] = {}
        self.kv: Dict[Tuple[str, str], bytes] = {}
        self.actors: Dict[ActorID, ActorRecord] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.pgs: Dict[PlacementGroupID, PGRecord] = {}
        self.jobs: Dict[JobID, dict] = {}
        self.job_counter = 0
        # oid -> {"attempt": committed execution epoch, "nodes": holders};
        # seal-once at cluster scope: only the newest attempt's copies are
        # visible, displaced copies are deleted at their nodes (reference:
        # plasma's seal-once, obj_lifecycle_mgr.cc)
        self.object_dir: Dict[bytes, dict] = {}
        self._freed_ring: "deque[bytes]" = deque()  # bounded tombstone FIFO
        self.subs: Dict[int, Tuple[ServerConnection, Set[str]]] = {}
        self.conn_jobs: Dict[int, JobID] = {}
        self._worker_clients: Dict[str, RetryingRpcClient] = {}
        # unplaceable demand shapes -> autoscaler (reference: the v2
        # gcs_autoscaler_state_manager.cc cluster-state view)
        self.pending_demands: Dict[tuple, dict] = {}
        self.node_last_used: Dict[NodeID, float] = {}
        self.node_num_leases: Dict[NodeID, int] = {}
        # structured event ring (reference: util/event.cc + export events
        # aggregated by the dashboard) — bounded, newest at the right
        self.events = deque(maxlen=1000)
        # task lifecycle events, sharded + pipelined (reference:
        # gcs_task_manager.cc; the sharding is ours — see ShardedTaskEvents)
        self.task_manager = ShardedTaskEvents()
        # cluster health plane: metrics time-series history + the
        # stuck/straggler scanner's latest report
        self.metrics_history = MetricsHistory()
        # per-job goodput aggregation over the workers' ledger payloads
        self.goodput_ledger = GoodputLedger()
        self._health: dict = {"ts": 0.0, "status": "unknown",
                              "findings": [], "scan_count": 0}
        self._health_warn_ts: Dict[tuple, float] = {}
        self._background: List[asyncio.Task] = []
        self.start_time = time.time()
        self._load_init_data()

    # ------------------------------------------------------------------
    # persistence (reference: gcs_init_data.cc replay + store_client/)
    # ------------------------------------------------------------------

    def _load_init_data(self):
        """Reload all durable tables from the store (no-op for a fresh
        in-memory store). Reference: GcsServer::Start loads GcsInitData
        before DoStart (gcs_server.cc:212)."""
        for key, blob in self.store.all("kv").items():
            ns, _, k = key.partition("\x00")
            self.kv[(ns, k)] = wire.loads(blob)
        for key, blob in self.store.all("nodes").items():
            info: NodeInfo = wire.loads(blob)
            self.nodes[info.node_id] = info
            if info.alive:
                self.node_available[info.node_id] = dict(info.total_resources)
                # grace period: raylets heartbeat in; health check reaps others
                self.node_last_seen[info.node_id] = time.monotonic()
                self.node_clients[info.node_id] = RetryingRpcClient(info.address)
        for key, blob in self.store.all("actors").items():
            record = ActorRecord.restore(wire.loads(blob))
            self.actors[record.actor_id] = record
            if record.name and record.state != "DEAD":
                self.named_actors[(record.namespace, record.name)] = record.actor_id
        for key, blob in self.store.all("pgs").items():
            pg = PGRecord.restore(wire.loads(blob))
            self.pgs[pg.spec.pg_id] = pg
        for key, blob in self.store.all("jobs").items():
            job = wire.loads(blob)
            self.jobs[JobID.from_hex(job["job_id"])] = job
        counter = self.store.get("meta", "job_counter")
        if counter is not None:
            self.job_counter = wire.loads(counter)
        if self.actors or self.nodes:
            logger.info(
                "GCS init data replayed: %d nodes, %d actors, %d pgs, %d jobs, %d kv",
                len(self.nodes), len(self.actors), len(self.pgs), len(self.jobs),
                len(self.kv))

    def _persist_kv(self, ns: str, key: str, value=None, delete: bool = False):
        skey = f"{ns}\x00{key}"
        if delete:
            self.store.delete("kv", skey)
        else:
            self.store.put("kv", skey, wire.dumps(value))

    def _persist_node(self, info: NodeInfo):
        if not info.alive:
            self.store.delete("nodes", info.node_id.hex())
        else:
            self.store.put("nodes", info.node_id.hex(), wire.dumps(info))

    def _persist_actor(self, record: ActorRecord):
        if record.state == "DEAD":
            # terminal: delete rather than replay-forever (the in-memory
            # record still serves info queries until the next restart)
            self.store.delete("actors", record.actor_id.hex())
        else:
            self.store.put("actors", record.actor_id.hex(),
                           wire.dumps(record.dump()))

    def _persist_pg(self, pg: PGRecord):
        if pg.state == "REMOVED":
            self.store.delete("pgs", pg.spec.pg_id.hex())
        else:
            self.store.put("pgs", pg.spec.pg_id.hex(), wire.dumps(pg.dump()))

    def _persist_job(self, job: dict):
        if job["state"] == "FINISHED":
            self.store.delete("jobs", job["job_id"])
        else:
            self.store.put("jobs", job["job_id"], wire.dumps(job))

    async def start(self) -> str:
        addr = await self.server.start()
        self._background.append(spawn(self._health_check_loop(),
                                      what="gcs health-check loop"))
        # merge thread for task-event ingest + read handoff (off-loop)
        self.task_manager._ensure_thread()
        self._background.append(spawn(self._metrics_history_loop(),
                                      what="gcs metrics-history sampler"))
        self._background.append(spawn(self._resource_view_flush_loop(),
                                      what="gcs resource-view flusher"))
        self._background.append(spawn(self._health_monitor_loop(),
                                      what="gcs health-monitor scanner"))
        self._background.append(spawn(self._ckpt_sweep_loop(),
                                      what="gcs ckpt retention sweeper"))
        # resume interrupted scheduling work from replayed init data
        for record in self.actors.values():
            if record.state in ("PENDING_CREATION", "RESTARTING"):
                if record.address:
                    # a creation was in flight when we died: probe before
                    # rescheduling so we never run two instances
                    spawn(self._recover_creating_actor(record),
                          what="actor creation recovery")
                else:
                    spawn(self._schedule_actor(record), what="actor scheduling")
        for job_id, job in list(self.jobs.items()):
            if job["state"] == "RUNNING":
                spawn(self._reap_job_if_driver_gone(job_id, job),
                      what="job reap probe")
        for pg in self.pgs.values():
            if pg.state in ("PENDING", "RESCHEDULING"):
                spawn(self._schedule_pg(pg), what="placement-group scheduling")
        logger.info("GCS listening on %s", addr)
        return addr

    async def stop(self):
        for t in self._background:
            t.cancel()
        self.task_manager.stop()
        await self.server.stop()
        self.store.close()

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    async def _handle(self, method: str, payload: bytes, conn) -> bytes:
        fn = getattr(self, f"_rpc_{method}", None)
        if fn is None:
            raise RpcError(f"GCS: unknown method {method}")
        req = wire.loads(payload) if payload else {}
        resp = await fn(req, conn)
        return wire.dumps(resp)

    def _publish(self, channel: str, message: dict):
        payload = wire.dumps(message)
        for conn, channels in list(self.subs.values()):
            if channel in channels:
                spawn(conn.push(channel, payload), what="pubsub push")

    async def _on_disconnect(self, conn: ServerConnection):
        self.subs.pop(conn.conn_id, None)
        job_id = self.conn_jobs.pop(conn.conn_id, None)
        if job_id is not None and job_id in self.jobs:
            await self._finish_job(job_id)

    # ------------------------------------------------------------------
    # nodes / health
    # ------------------------------------------------------------------

    async def _rpc_RegisterNode(self, req, conn):
        info: NodeInfo = req["info"]
        self.nodes[info.node_id] = info
        self.node_available[info.node_id] = dict(info.total_resources)
        self.node_last_seen[info.node_id] = time.monotonic()
        self.node_clients[info.node_id] = RetryingRpcClient(info.address)
        self._persist_node(info)
        logger.info("node %s registered: %s labels=%s", info.node_id.hex()[:8],
                    info.total_resources, info.labels)
        self._publish("nodes", {"event": "added", "node": info.to_dict()})
        # membership changes flush immediately (spillback views must learn
        # about a new peer now); coalescing is for availability flapping
        self._view_dirty.add(info.node_id)
        self._flush_resource_views()
        self._record_event("node", "INFO", "node registered",
                           node_id=info.node_id.hex(),
                           resources=dict(info.total_resources))
        return {"status": "ok"}

    async def _rpc_Heartbeat(self, req, conn):
        node_id: NodeID = req["node_id"]
        if node_id not in self.nodes:
            return {"status": "unknown_node"}  # raylet should re-register
        self.node_last_seen[node_id] = time.monotonic()
        self.node_available[node_id] = req["available"]
        self.node_num_leases[node_id] = req.get("num_leases", 0)
        if self._node_used(node_id) or node_id not in self.node_last_used:
            self.node_last_used[node_id] = time.monotonic()
        # syncer: broadcast availability DELTAS to subscribed raylets so
        # their local schedulers can spill leases peer-to-peer without a
        # per-lease GCS round trip (reference: ray_syncer.h:89 resource
        # views over bidi streams). Changes only mark the node dirty here;
        # the tick loop folds all dirty nodes into ONE batched publish
        # (delta suppression re-checked at flush: a value that flapped
        # back to the published view inside the tick publishes nothing)
        if self._last_view_pub.get(node_id) != req["available"]:
            self._view_dirty.add(node_id)
        # parked lease shapes feed the autoscaler's demand view (the
        # two-level path no longer touches PickNode for schedulable work)
        for shape in req.get("pending_shapes", ()):
            self._record_demand(shape["resources"], shape.get("selector", {}),
                                shape.get("waiter_id", ""))
        return {"status": "ok"}

    def _flush_resource_views(self):
        """Fold every dirty node into one batched ``resource_view`` publish
        carrying its LATEST view (subscribers apply entries idempotently,
        so intermediate states are safely elided). Delta suppression runs
        here, not at mark time: only views that still differ from the last
        broadcast actually ship."""
        if not self._view_dirty:
            return
        views = []
        for node_id in list(self._view_dirty):
            self._view_dirty.discard(node_id)
            info = self.nodes.get(node_id)
            if info is None:
                self._last_view_pub.pop(node_id, None)
                continue
            entry = self._view_entry(node_id)
            if not info.alive:
                self._last_view_pub.pop(node_id, None)
                views.append(entry)
                continue
            if self._last_view_pub.get(node_id) == entry["available"]:
                continue
            self._last_view_pub[node_id] = dict(entry["available"])
            views.append(entry)
        if views:
            self._publish("resource_view", {"views": views})

    async def _resource_view_flush_loop(self):
        tick = RAY_CONFIG.gcs_resource_view_tick_s
        while True:
            await asyncio.sleep(tick)
            try:
                self._flush_resource_views()
            except Exception:
                logger.exception("resource-view flush failed")

    def _view_entry(self, node_id: NodeID) -> dict:
        info = self.nodes[node_id]
        return {
            "node_id": node_id.hex(),
            "address": info.address,
            "available": dict(self.node_available.get(node_id, {})),
            "total": dict(info.total_resources),
            "labels": dict(info.labels),
            "alive": info.alive,
        }

    async def _rpc_GetAllNodes(self, req, conn):
        return {"nodes": [
            {**n.to_dict(),
             "available": dict(self.node_available.get(n.node_id, {}))}
            for n in self.nodes.values()]}

    async def _rpc_GetClusterResources(self, req, conn):
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for nid, info in self.nodes.items():
            if not info.alive:
                continue
            for k, v in info.total_resources.items():
                total[k] = total.get(k, 0.0) + v
            for k, v in self.node_available.get(nid, {}).items():
                avail[k] = avail.get(k, 0.0) + v
        return {"total": total, "available": avail}

    async def _rpc_DrainNode(self, req, conn):
        node_id: NodeID = req["node_id"]
        await self._mark_node_dead(node_id, "drained")
        return {"status": "ok"}

    async def _health_check_loop(self):
        period = RAY_CONFIG.health_check_period_ms / 1000.0
        timeout = RAY_CONFIG.health_check_timeout_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node_id, info in list(self.nodes.items()):
                if info.alive and now - self.node_last_seen.get(node_id, now) > timeout:
                    await self._mark_node_dead(node_id, "health check timeout")

    async def _mark_node_dead(self, node_id: NodeID, reason: str):
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        self.node_available.pop(node_id, None)
        self._persist_node(info)
        logger.warning("node %s dead: %s", node_id.hex()[:8], reason)
        self._publish("nodes", {"event": "removed", "node_id": node_id.hex(), "reason": reason})
        # death flushes immediately: spillback must stop targeting it now
        self._view_dirty.add(node_id)
        self._flush_resource_views()
        self._record_event("node", "ERROR", f"node dead: {reason}",
                           node_id=node_id.hex())
        # drop object locations on that node; keep the committed-attempt
        # tombstone so a partitioned zombie's stale announce can't
        # re-register an older epoch as current
        for oid, entry in list(self.object_dir.items()):
            entry["nodes"].discard(node_id)
        # fail over actors that lived there
        for record in list(self.actors.values()):
            if record.node_id == node_id and record.state in ("ALIVE", "PENDING_CREATION"):
                await self._on_actor_worker_lost(record, f"node died: {reason}")
        # reschedule placement groups with bundles there
        for pg in self.pgs.values():
            if pg.state == "CREATED" and any(n == node_id for n in pg.bundle_nodes):
                pg.state = "RESCHEDULING"
                spawn(self._schedule_pg(pg), what="placement-group scheduling")

    # ------------------------------------------------------------------
    # kv
    # ------------------------------------------------------------------

    async def _rpc_KVPut(self, req, conn):
        key = (req.get("ns", ""), req["key"])
        if not req.get("overwrite", True) and key in self.kv:
            return {"added": False}
        self.kv[key] = req["value"]
        self._persist_kv(key[0], key[1], req["value"])
        self._observe_kv(key[0], key[1], req["value"])
        return {"added": True}

    def _observe_kv(self, ns: str, key: str, value):
        """Tap metric-snapshot and goodput-ledger puts into their
        aggregators (the reporters keep their single KV write; history
        costs them nothing)."""
        if ns == "metrics":
            try:
                self.metrics_history.observe_payload(key, wire.loads(value))
            except Exception as e:
                logger.debug("undecodable metrics payload %s: %s", key, e)
        elif ns == "goodput":
            try:
                self.goodput_ledger.observe(key, wire.loads(value))
            except Exception as e:
                logger.debug("undecodable goodput payload %s: %s", key, e)

    async def _rpc_KVGet(self, req, conn):
        return {"value": self.kv.get((req.get("ns", ""), req["key"]))}

    async def _rpc_KVMultiPut(self, req, conn):
        """Batched puts: N keys (possibly across namespaces) in one round
        trip, so high-rate mirrors (metrics, pool stats, store stats) don't
        serialize one handler dispatch per key."""
        added = 0
        for item in req.get("items") or ():
            key = (item.get("ns", ""), item["key"])
            self.kv[key] = item["value"]
            self._persist_kv(key[0], key[1], item["value"])
            self._observe_kv(key[0], key[1], item["value"])
            added += 1
        return {"added": added}

    async def _rpc_KVMultiGet(self, req, conn):
        ns = req.get("ns", "")
        return {"values": {k: self.kv.get((ns, k))
                           for k in req.get("keys") or ()}}

    async def _rpc_KVDel(self, req, conn):
        prefix = req.get("prefix", False)
        ns = req.get("ns", "")
        if prefix:
            keys = [k for k in self.kv if k[0] == ns and k[1].startswith(req["key"])]
            for k in keys:
                del self.kv[k]
                self._persist_kv(k[0], k[1], delete=True)
            return {"deleted": len(keys)}
        if self.kv.pop((ns, req["key"]), None) is not None:
            self._persist_kv(ns, req["key"], delete=True)
            return {"deleted": 1}
        return {"deleted": 0}

    async def _rpc_KVKeys(self, req, conn):
        ns = req.get("ns", "")
        prefix = req.get("prefix", "")
        return {"keys": [k[1] for k in self.kv if k[0] == ns and k[1].startswith(prefix)]}

    # ------------------------------------------------------------------
    # jobs
    # ------------------------------------------------------------------

    async def _rpc_RegisterDriver(self, req, conn):
        self.job_counter += 1
        job_id = JobID.from_int(self.job_counter)
        self.jobs[job_id] = {
            "job_id": job_id.hex(),
            "driver_address": req.get("address", ""),
            "namespace": req.get("namespace", "default"),
            "start_time": time.time(),
            "state": "RUNNING",
            "entrypoint": req.get("entrypoint", ""),
        }
        self.conn_jobs[conn.conn_id] = job_id
        self.store.put("meta", "job_counter", wire.dumps(self.job_counter))
        self._persist_job(self.jobs[job_id])
        return {"job_id": job_id.binary()}

    async def _rpc_ReattachDriver(self, req, conn):
        """A driver re-binds its (new) connection to its existing job after a
        GCS restart, so driver-disconnect job cleanup keeps working."""
        job_id = JobID(req["job_id"])
        job = self.jobs.get(job_id)
        if job is not None and job["state"] == "RUNNING":
            self.conn_jobs[conn.conn_id] = job_id
            return {"status": "ok"}
        return {"status": "unknown_job"}

    async def _finish_job(self, job_id: JobID):
        job = self.jobs.get(job_id)
        if job is None or job["state"] == "FINISHED":
            return
        job["state"] = "FINISHED"
        job["end_time"] = time.time()
        self._persist_job(job)
        logger.info("job %s finished; reaping its actors", job_id.hex())
        for record in list(self.actors.values()):
            if record.job_id == job_id and record.lifetime != "detached" and record.state != "DEAD":
                await self._kill_actor(record, no_restart=True, reason="owning job finished")
        for pg in list(self.pgs.values()):
            if pg.spec.creator_job == job_id and pg.spec.lifetime != "detached":
                await self._remove_pg(pg)
        # purge the job's object-directory entries (incl. empty tombstones
        # kept for epoch fencing); ids embed the job id at the task-id tail
        from ray_tpu._private.ids import TaskID

        jid = job_id.binary()
        for oid in [o for o in self.object_dir
                    if o[TaskID.SIZE - len(jid) : TaskID.SIZE] == jid]:
            del self.object_dir[oid]

    # ------------------------------------------------------------------
    # pubsub
    # ------------------------------------------------------------------

    def _record_event(self, source: str, severity: str, message: str,
                      **metadata):
        event = {"ts": time.time(), "source": source, "severity": severity,
                 "message": message, "metadata": metadata}
        self.events.append(event)
        self._publish("events", event)

    async def _rpc_ReportEvent(self, req, conn):
        ev = dict(req["event"])
        self.events.append(ev)
        self._publish("events", ev)
        return {"status": "ok"}

    async def _rpc_GetEvents(self, req, conn):
        out = list(self.events)
        if req.get("source"):
            out = [e for e in out if e.get("source") == req["source"]]
        if req.get("severity"):
            want = str(req["severity"]).upper()
            out = [e for e in out if e.get("severity") == want]
        return {"events": out[-int(req.get("limit") or 200):]}

    # -- task lifecycle events (reference: gcs_task_manager.cc RPCs) --

    async def _rpc_AddTaskEvents(self, req, conn):
        # enqueue-and-return: the per-shard drain tasks merge in the
        # background so a 5k tasks/s burst costs each reporter an enqueue,
        # not a synchronous merge on the shared handler path
        self.task_manager.ingest(req.get("events") or [],
                                 int(req.get("dropped") or 0))
        return {"status": "ok"}

    async def _rpc_ListTasks(self, req, conn):
        # read handoff: the merge thread runs the query after everything
        # already enqueued has merged — the GCS loop never pays the merge
        job_id, name = req.get("job_id"), req.get("name")
        state, limit = req.get("state"), int(req.get("limit") or 200)
        return {"tasks": await self.task_manager.read(
            lambda tm: tm.list_tasks(job_id=job_id, name=name, state=state,
                                     limit=limit))}

    async def _rpc_GetTask(self, req, conn):
        tid = req["task_id"]
        return {"task": await self.task_manager.read(
            lambda tm: tm.get_task(tid))}

    async def _rpc_SummarizeTasks(self, req, conn):
        job_id = req.get("job_id")
        return await self.task_manager.read(
            lambda tm: tm.summarize(job_id=job_id))

    async def _rpc_GetTimeline(self, req, conn):
        """Chrome-trace (Perfetto) JSON of the task flow graph, filterable
        by job and time window; span records from the trace table ride
        along so built-in hot-path spans land in the same trace. Built on
        the merge thread — a timeline scrape never stalls ingest."""
        job_id = req.get("job_id")
        start_ts, end_ts = req.get("start_ts"), req.get("end_ts")
        limit = int(req.get("limit") or 5000)
        blobs: List[bytes] = []
        if req.get("spans", True):
            # snapshot the blob list on the loop (self.kv belongs to it);
            # decode off-loop on the merge thread
            blobs = [v for (ns, k), v in self.kv.items()
                     if ns == "trace" and k.startswith("spans_") and v]

        def _build(tm):
            spans: List[dict] = []
            for blob in blobs:
                try:
                    spans.extend(wire.loads(blob))
                except Exception as e:
                    logger.debug("undecodable span blob skipped: %s", e)
            records = tm.list_tasks(job_id=job_id, limit=limit)
            return build_timeline(records, spans,
                                  start_ts=start_ts, end_ts=end_ts)

        return await self.task_manager.read(_build)

    async def _rpc_Subscribe(self, req, conn):
        channels = set(req["channels"])
        existing = self.subs.get(conn.conn_id)
        if existing:
            existing[1].update(channels)
        else:
            self.subs[conn.conn_id] = (conn, channels)
        return {"status": "ok"}

    async def _rpc_Publish(self, req, conn):
        self._publish(req["channel"], req["message"])
        return {"status": "ok"}

    # ------------------------------------------------------------------
    # object directory
    # ------------------------------------------------------------------

    async def _rpc_ObjectLocAdd(self, req, conn):
        node_id = req["node_id"]
        attempt = req.get("attempt", 0)
        sizes = req.get("sizes") or {}
        for oid in req["oids"]:
            size = sizes.get(oid, 0)
            entry = self.object_dir.get(oid)
            if entry is not None and size:
                entry["size"] = size
            if entry is None:
                self.object_dir[oid] = {"attempt": attempt, "nodes": {node_id},
                                        "size": size}
            elif attempt > entry["attempt"]:
                displaced = entry["nodes"] - {node_id}
                self.object_dir[oid] = {"attempt": attempt, "nodes": {node_id},
                                        "size": size or entry.get("size", 0)}
                if displaced:
                    spawn(self._delete_stale_copies(oid, attempt, displaced),
                          what="stale-copy delete")
            elif attempt == entry["attempt"]:
                entry["nodes"].add(node_id)
            else:
                # stale-epoch announce: reject, and tell that node to drop it
                spawn(self._delete_stale_copies(
                    oid, entry["attempt"], {node_id}), what="stale-copy delete")
        return {"status": "ok"}

    async def _delete_stale_copies(self, oid: bytes, attempt: int, nodes):
        for node_id in nodes:
            client = self.node_clients.get(node_id)
            info = self.nodes.get(node_id)
            if client is None or info is None or not info.alive:
                continue
            try:
                await client.call("StoreDeleteStale", wire.dumps(
                    {"oid": oid, "attempt": attempt}), timeout=10.0, retries=1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("StoreDeleteStale(%s) to %s failed: %s",
                             oid.hex()[:8], node_id.hex()[:8], e)

    async def _rpc_ObjectLocRemove(self, req, conn):
        for oid in req["oids"]:
            entry = self.object_dir.get(oid)
            if entry:
                # keep the committed-attempt tombstone (empty node set) so a
                # stale-epoch announce can't re-register; purged at job end
                entry["nodes"].discard(req["node_id"])
        return {"status": "ok"}

    _FREED_EPOCH = 1 << 62  # tombstone attempt: beats any real epoch

    async def _rpc_ObjectFree(self, req, conn):
        """Owner-initiated cluster-wide free: zero references remain, so the
        copies on every holding node are deleted and the entry becomes a
        freed tombstone (reference: the owner's delete fan-out on ref-count
        zero). The tombstone's infinite epoch makes any late announce (e.g.
        a pull that completed mid-free) route into the stale-copy deletion
        path instead of resurrecting the object.

        Tombstones are BOUNDED: a FIFO ring of gcs_freed_tombstone_cap ids
        (oldest evicted first), not held until job end — a long-running job
        with high object churn would otherwise grow the directory without
        limit. Evicting a tombstone only re-opens the (already tiny) window
        for an announce delayed past tens of thousands of subsequent frees."""
        per_node: Dict[NodeID, List[bytes]] = {}
        for oid in req["oids"]:
            entry = self.object_dir.get(oid)
            if entry:
                for node_id in entry["nodes"]:
                    per_node.setdefault(node_id, []).append(oid)
            self.object_dir[oid] = {"attempt": self._FREED_EPOCH,
                                    "nodes": set()}
            self._freed_ring.append(oid)
        cap = RAY_CONFIG.gcs_freed_tombstone_cap
        while len(self._freed_ring) > cap:
            old = self._freed_ring.popleft()
            stale = self.object_dir.get(old)
            if stale is not None and stale["attempt"] == self._FREED_EPOCH:
                del self.object_dir[old]
        for node_id, oids in per_node.items():
            client = self.node_clients.get(node_id)
            info = self.nodes.get(node_id)
            if client is None or info is None or not info.alive:
                continue
            try:
                await client.call("StoreDelete", wire.dumps({"oids": oids}),
                                  timeout=10.0, retries=1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("StoreDelete(%d oids) to %s failed: %s",
                             len(oids), node_id.hex()[:8], e)
        return {"status": "ok"}

    async def _rpc_ObjectLocGet(self, req, conn):
        out = []
        entry = self.object_dir.get(req["oid"])
        for node_id in (entry["nodes"] if entry else ()):  # alive nodes only
            info = self.nodes.get(node_id)
            if info is not None and info.alive:
                out.append({"node_id": node_id.hex(), "address": info.address})
        return {"locations": out, "attempt": entry["attempt"] if entry else 0,
                "size": entry.get("size", 0) if entry else 0}

    # ------------------------------------------------------------------
    # scheduling helpers
    # ------------------------------------------------------------------

    def _feasible_nodes(self, resources: Dict[str, float], selector: Dict[str, str],
                        check_available: bool = True) -> List[NodeID]:
        out = []
        for node_id, info in self.nodes.items():
            if not info.alive:
                continue
            if selector and not label_match(info.labels, selector):
                continue
            pool = self.node_available.get(node_id, {}) if check_available else info.total_resources
            if resources_ge(pool, resources):
                out.append(node_id)
        return out

    def _pick_node(self, resources: Dict[str, float], selector: Dict[str, str],
                   waiter_id: str = "") -> Optional[NodeID]:
        """Hybrid policy: pack onto the most-utilized feasible node below the
        spread threshold, else least-utilized (reference:
        raylet/scheduling/policy/hybrid_scheduling_policy.cc)."""
        feasible = self._feasible_nodes(resources, selector)
        if not feasible:
            # fall back to nodes that are feasible by total resources (queue there)
            feasible = self._feasible_nodes(resources, selector, check_available=False)
            if not feasible:
                self._record_demand(resources, selector, waiter_id)
                return None
        def utilization(nid):
            info = self.nodes[nid]
            avail = self.node_available.get(nid, {})
            fracs = [
                1.0 - avail.get(k, 0.0) / v
                for k, v in info.total_resources.items()
                if v > 0
            ]
            return max(fracs) if fracs else 0.0
        scored = sorted(feasible, key=lambda nid: (utilization(nid), nid.hex()))
        threshold = RAY_CONFIG.scheduler_spread_threshold
        packed = [nid for nid in scored if utilization(nid) < threshold]
        if packed:
            return packed[-1]  # most utilized below threshold -> pack
        return scored[0]  # least utilized -> spread

    async def _rpc_PickNode(self, req, conn):
        """Owner-side lease policy support: pick a node for a task's resource
        shape + label selector (reference: owner lease_policy.cc + raylet
        spillback; centralized here on the GCS resource view)."""
        strat = req.get("strategy")
        if strat == "SPREAD":
            feasible = self._feasible_nodes(req["resources"], req.get("selector", {}))
            if feasible:
                idx = req.get("spread_hint", 0) % len(feasible)
                nid = sorted(feasible, key=lambda n: n.hex())[idx]
                return {"node": self._node_addr(nid)}
        nid = self._pick_node(req["resources"], req.get("selector", {}),
                              waiter_id=req.get("waiter_id", ""))
        return {"node": self._node_addr(nid) if nid else None}

    def _node_addr(self, nid: NodeID) -> dict:
        info = self.nodes[nid]
        return {"node_id": nid.hex(), "address": info.address}

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    def _worker_client(self, address: str) -> RetryingRpcClient:
        client = self._worker_clients.get(address)
        if client is None:
            client = RetryingRpcClient(address)
            self._worker_clients[address] = client
        return client

    async def _rpc_CreateActor(self, req, conn):
        spec: TaskSpec = req["spec"]
        opts = spec.actor_options
        if opts.name:
            key = (opts.namespace or "default", opts.name)
            existing = self.named_actors.get(key)
            if existing is not None and self.actors[existing].state != "DEAD":
                if opts.get_if_exists:
                    return {"status": "exists", "info": self.actors[existing].info()}
                return {"status": "name_taken"}
        actor_id = spec.actor_id
        record = ActorRecord(actor_id, spec)
        record.class_name = req.get("class_name", "")
        self.actors[actor_id] = record
        if record.name:
            self.named_actors[(record.namespace, record.name)] = actor_id
        self._persist_actor(record)
        spawn(self._schedule_actor(record), what="actor scheduling")
        return {"status": "ok", "info": record.info()}

    async def _schedule_actor(self, record: ActorRecord):
        """Lease a worker on a feasible node and push the creation task.

        Reference: gcs_actor_scheduler.cc (lease-based actor scheduling).
        """
        spec = record.spec
        opts = spec.actor_options
        resources = opts.required_resources()
        deadline = time.monotonic() + 3600.0
        warned = False
        while record.state in ("PENDING_CREATION", "RESTARTING") and not record.pending_kill:
            node_id = None
            if opts.placement_group is not None:
                node_id = self._pg_bundle_node(opts)
            else:
                strat = opts.scheduling_strategy
                selector = dict(opts.label_selector)
                if strat is not None and hasattr(strat, "hard"):
                    selector.update(strat.hard)
                if strat is not None and hasattr(strat, "node_id"):
                    node_id = NodeID.from_hex(strat.node_id)
                    if getattr(strat, "soft", False) and (
                            node_id not in self.nodes
                            or not self.nodes[node_id].alive):
                        # soft affinity: preferred node gone — fall back to
                        # the normal pick instead of pinning to a corpse
                        node_id = self._pick_node(
                            resources, selector,
                            waiter_id=record.actor_id.hex())
                else:
                    node_id = self._pick_node(
                        resources, selector,
                        waiter_id=record.actor_id.hex())
            if node_id is None or node_id not in self.nodes or not self.nodes[node_id].alive:
                if not warned and time.monotonic() > deadline - 3590:
                    pass
                if not warned:
                    logger.warning(
                        "actor %s infeasible (resources=%s); waiting for nodes",
                        record.actor_id.hex()[:8], resources)
                    warned = True
                await asyncio.sleep(0.5)
                if time.monotonic() > deadline:
                    record.state = "DEAD"
                    record.death_cause = "scheduling timed out"
                    self._publish_actor(record)
                    return
                continue
            try:
                # optimistic view update: concurrent _schedule_actor loops
                # all read node_available, which only refreshes on 1 Hz
                # heartbeats — without this decrement a 100-actor burst
                # herds onto ONE node and the overflow parks at its raylet
                # for the whole worker_start_timeout while other nodes sit
                # empty (the next heartbeat corrects any drift)
                avail = self.node_available.get(node_id)
                if avail is not None:
                    for k, v in resources.items():
                        avail[k] = avail.get(k, 0.0) - v
                client = self.node_clients[node_id]
                reply = wire.loads(await client.call("RequestWorkerLease", wire.dumps({
                    "resources": resources,
                    "label_selector": opts.label_selector,
                    "job_id": spec.job_id,
                    "pg": (opts.placement_group.id.binary()
                           if opts.placement_group is not None else None),
                    "bundle_index": opts.placement_group_bundle_index,
                    "for_actor": record.actor_id.binary(),
                    "runtime_env": opts.runtime_env,
                }), timeout=RAY_CONFIG.worker_start_timeout_s + 30))
                if reply.get("status") != "granted":
                    await asyncio.sleep(0.2)
                    continue
                worker_addr = reply["worker_address"]
                # durably note the in-flight creation BEFORE pushing it, so a
                # GCS crash during creation can probe this worker instead of
                # scheduling a second instance (see _recover_creating_actor)
                record.address = worker_addr
                record.node_id = node_id
                record.lease_id = reply.get("lease_id", "")
                self._persist_actor(record)
                wreply = wire.loads(await self._worker_client(worker_addr).call(
                    "PushTask", wire.dumps({"spec": spec}), timeout=600.0))
                if wreply.get("status") != "ok":
                    logger.warning("actor %s creation failed on %s: %s",
                                   record.actor_id.hex()[:8], worker_addr,
                                   wreply.get("error", "")[:500])
                    record.state = "DEAD"
                    record.address = ""
                    record.node_id = None
                    record.death_cause = wreply.get("error", "creation task failed")
                    self._publish_actor(record)
                    return
                record.state = "ALIVE"
                record.address = worker_addr
                record.node_id = node_id
                self._publish_actor(record)
                return
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.warning("actor %s scheduling attempt failed: %s",
                               record.actor_id.hex()[:8], e)
                await asyncio.sleep(0.3)

    async def _recover_creating_actor(self, record: ActorRecord):
        """After an init-data replay, a PENDING_CREATION/RESTARTING record
        with an address means a creation push was in flight when we died.
        Probe the worker: if the actor is instantiated there, adopt it as
        ALIVE; otherwise release the orphaned lease and reschedule."""
        addr = record.address
        try:
            reply = wire.loads(await self._worker_client(addr).call(
                "CheckActor", wire.dumps({"actor_id": record.actor_id.binary()}),
                timeout=10.0, retries=1, connect_timeout=2.0, presend_retries=1))
            if reply.get("hosting"):
                record.state = "ALIVE"
                self._publish_actor(record)
                logger.info("actor %s adopted on %s after GCS restart",
                            record.actor_id.hex()[:8], addr)
                return
        except (RpcError, asyncio.TimeoutError, OSError) as e:
            logger.debug("actor %s adoption probe to %s failed: %s",
                         record.actor_id.hex()[:8], addr, e)
        # not there: give the lease back (if the raylet is still up), then
        # schedule from scratch
        if record.lease_id and record.node_id in self.node_clients:
            try:
                await self.node_clients[record.node_id].call(
                    "ReturnWorkerLease", wire.dumps({"lease_id": record.lease_id}),
                    timeout=5.0, retries=1)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("ReturnWorkerLease for actor %s failed: %s",
                             record.actor_id.hex()[:8], e)
        record.address = ""
        record.node_id = None
        record.lease_id = ""
        self._persist_actor(record)
        spawn(self._schedule_actor(record), what="actor scheduling")

    async def _reap_job_if_driver_gone(self, job_id: JobID, job: dict):
        """Replayed RUNNING jobs lost their connection binding when the GCS
        died; poll the driver until it either reattaches (conn binding
        restored) or turns out dead (job finished + actors reaped)."""
        grace = RAY_CONFIG.gcs_driver_reattach_grace_s
        while True:
            await asyncio.sleep(grace)
            if job_id not in self.jobs or self.jobs[job_id]["state"] != "RUNNING":
                return
            if any(j == job_id for j in self.conn_jobs.values()):
                return  # driver reattached; disconnect cleanup is armed again
            addr = job.get("driver_address", "")
            if addr:
                try:
                    await self._worker_client(addr).call(
                        "Ping", b"", timeout=5.0, retries=1,
                        connect_timeout=3.0, presend_retries=1)
                    continue  # driver alive but quiet; keep polling
                except (RpcError, asyncio.TimeoutError, OSError) as e:
                    logger.debug("driver ping %s failed (job cleanup "
                                 "candidate): %s", addr, e)
            logger.warning("job %s driver gone after GCS restart; finishing it",
                           job_id.hex())
            await self._finish_job(job_id)
            return

    def _pg_bundle_node(self, opts) -> Optional[NodeID]:
        pg_id = opts.placement_group.id
        pg = self.pgs.get(pg_id)
        if pg is None or pg.state != "CREATED":
            return None
        idx = opts.placement_group_bundle_index
        if idx < 0:
            idx = 0
        return pg.bundle_nodes[idx]

    def _publish_actor(self, record: ActorRecord):
        self._persist_actor(record)
        self._publish("actors", {"event": "state", "info": record.info()})

    async def _on_actor_worker_lost(self, record: ActorRecord, reason: str):
        if record.state == "DEAD":
            return
        if record.pending_kill or (record.max_restarts != -1
                                   and record.restarts_used >= record.max_restarts):
            record.state = "DEAD"
            record.death_cause = reason
            self._publish_actor(record)
            self._record_event("actor", "ERROR", f"actor dead: {reason}",
                               actor_id=record.actor_id.hex(),
                               class_name=record.class_name)
            return
        record.restarts_used += 1
        record.state = "RESTARTING"
        self._record_event("actor", "WARNING",
                           f"actor restarting ({reason})",
                           actor_id=record.actor_id.hex(),
                           restarts_used=record.restarts_used)
        record.address = ""
        record.node_id = None
        self._publish_actor(record)
        spawn(self._schedule_actor(record), what="actor scheduling")

    async def _rpc_GetActorInfo(self, req, conn):
        record = self.actors.get(ActorID(req["actor_id"]))
        return {"info": record.info() if record else None}

    async def _rpc_WaitActorReady(self, req, conn):
        actor_id = ActorID(req["actor_id"])
        deadline = time.monotonic() + req.get("timeout", 300.0)
        while time.monotonic() < deadline:
            record = self.actors.get(actor_id)
            if record is None:
                return {"info": None}
            if record.state in ("ALIVE", "DEAD"):
                return {"info": record.info()}
            await asyncio.sleep(0.05)
        return {"info": self.actors[actor_id].info() if actor_id in self.actors else None}

    async def _rpc_GetNamedActor(self, req, conn):
        key = (req.get("namespace", "default"), req["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None or self.actors[actor_id].state == "DEAD":
            return {"info": None}
        return {"info": self.actors[actor_id].info()}

    async def _rpc_ListActors(self, req, conn):
        return {"actors": [r.info() for r in self.actors.values()]}

    async def _rpc_KillActor(self, req, conn):
        record = self.actors.get(ActorID(req["actor_id"]))
        if record is None:
            return {"status": "not_found"}
        await self._kill_actor(record, req.get("no_restart", True), "ray_tpu.kill")
        return {"status": "ok"}

    async def _kill_actor(self, record: ActorRecord, no_restart: bool, reason: str):
        if no_restart:
            record.pending_kill = True
        address = record.address
        if record.state == "ALIVE" and record.node_id in self.node_clients and address:
            try:
                # best-effort: the raylet may already be dead (node loss not
                # yet detected) — fail FAST rather than burning the default
                # connect/presend retry budget per kill (a group shutdown
                # after node loss kills many actors back-to-back)
                await self.node_clients[record.node_id].call(
                    "KillWorker", wire.dumps({"worker_address": address}),
                    timeout=10.0, retries=0, connect_timeout=2.0,
                    presend_retries=0)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("KillWorker %s on %s failed (raylet likely "
                             "dead): %s", address, record.node_id.hex()[:8], e)
        if no_restart:
            record.state = "DEAD"
            record.death_cause = reason
            if (record.namespace, record.name) in self.named_actors:
                if self.named_actors[(record.namespace, record.name)] == record.actor_id:
                    del self.named_actors[(record.namespace, record.name)]
            self._publish_actor(record)
            self._record_event("actor", "INFO", f"actor killed: {reason}",
                               actor_id=record.actor_id.hex(),
                               class_name=record.class_name)

    async def _rpc_WorkerDied(self, req, conn):
        """Raylet tells us a worker process exited (reference: raylet→GCS
        worker failure report; owners learn via the `workers` channel)."""
        address = req["worker_address"]
        self._publish("workers", {"event": "died", "worker_address": address,
                                  "node_id": req.get("node_id")})
        reason = req.get("reason", "worker died")
        self._record_event(
            "worker", "ERROR" if "OOM" in reason else "WARNING",
            f"worker died: {reason}", worker_address=address,
            node_id=req.get("node_id"))
        for record in self.actors.values():
            if record.address == address and record.state == "ALIVE":
                await self._on_actor_worker_lost(record, reason)
        return {"status": "ok"}

    # ------------------------------------------------------------------
    # placement groups (2PC reserve/commit)
    # ------------------------------------------------------------------

    async def _rpc_CreatePlacementGroup(self, req, conn):
        spec: PlacementGroupSpec = req["spec"]
        pg = PGRecord(spec)
        self.pgs[spec.pg_id] = pg
        self._persist_pg(pg)
        spawn(self._schedule_pg(pg), what="placement-group scheduling")
        return {"status": "ok"}

    async def _rpc_WaitPlacementGroupReady(self, req, conn):
        pg = self.pgs.get(PlacementGroupID(req["pg_id"]))
        if pg is None:
            return {"status": "not_found"}
        try:
            await asyncio.wait_for(pg.ready_event.wait(), req.get("timeout", 300.0))
            return {"status": "ready" if pg.state == "CREATED" else pg.state,
                    "bundle_nodes": [n.hex() if n else "" for n in pg.bundle_nodes]}
        except asyncio.TimeoutError:
            return {"status": "timeout"}

    async def _rpc_GetPlacementGroup(self, req, conn):
        pg = self.pgs.get(PlacementGroupID(req["pg_id"]))
        if pg is None:
            return {"info": None}
        return {"info": {
            "pg_id": pg.spec.pg_id.hex(),
            "state": pg.state,
            "strategy": pg.spec.strategy,
            "name": pg.spec.name,
            "bundles": [dict(b.resources) for b in pg.spec.bundles],
            "bundle_nodes": [n.hex() if n else "" for n in pg.bundle_nodes],
        }}

    async def _rpc_RemovePlacementGroup(self, req, conn):
        pg = self.pgs.get(PlacementGroupID(req["pg_id"]))
        if pg is not None:
            await self._remove_pg(pg)
        return {"status": "ok"}

    async def _remove_pg(self, pg: PGRecord):
        pg.state = "REMOVED"
        self._persist_pg(pg)
        released: set = set()
        for idx, node_id in enumerate(pg.bundle_nodes):
            if node_id is None or node_id in released \
                    or node_id not in self.node_clients:
                continue
            released.add(node_id)  # one release per node, not per bundle
            info = self.nodes.get(node_id)
            if info is not None and not info.alive:
                continue  # dead node: nothing to release
            try:
                # one retry for LIVE nodes (a swallowed transient failure
                # would leak the bundle reservation until raylet restart);
                # dead raylets still fail fast via the 2s connect bound
                await self.node_clients[node_id].call("ReleasePGBundles", wire.dumps(
                    {"pg_id": pg.spec.pg_id.binary()}), timeout=10.0,
                    retries=1, connect_timeout=2.0, presend_retries=0)
            except (RpcError, asyncio.TimeoutError, OSError) as e:
                logger.debug("ReleasePGBundles pg=%s to %s failed: %s",
                             pg.spec.pg_id.hex()[:8], node_id.hex()[:8], e)
        pg.ready_event.set()

    def _plan_pg(self, pg: PGRecord) -> Optional[List[NodeID]]:
        """Assign each bundle a node per strategy, against a scratch view."""
        spec = pg.spec
        scratch: Dict[NodeID, Dict[str, float]] = {
            nid: dict(self.node_available.get(nid, {}))
            for nid, info in self.nodes.items() if info.alive
        }
        assignment: List[Optional[NodeID]] = [None] * len(spec.bundles)

        def fits(nid, bundle: Bundle):
            info = self.nodes[nid]
            if bundle.label_selector and not label_match(info.labels, bundle.label_selector):
                return False
            return resources_ge(scratch[nid], bundle.resources)

        order = sorted(scratch.keys(), key=lambda n: n.hex())
        if spec.strategy in ("PACK", "STRICT_PACK"):
            # try to land everything on one node first
            for nid in order:
                trial = dict(scratch[nid])
                ok = True
                for b in spec.bundles:
                    info = self.nodes[nid]
                    if (b.label_selector and not label_match(info.labels, b.label_selector)) \
                            or not resources_ge(trial, b.resources):
                        ok = False
                        break
                    for k, v in b.resources.items():
                        trial[k] = trial.get(k, 0.0) - v
                if ok:
                    return [nid] * len(spec.bundles)
            if spec.strategy == "STRICT_PACK":
                return None
        if spec.strategy == "STRICT_SPREAD":
            used: Set[NodeID] = set()
            for i, b in enumerate(spec.bundles):
                placed = False
                for nid in order:
                    if nid in used or not fits(nid, b):
                        continue
                    assignment[i] = nid
                    used.add(nid)
                    placed = True
                    break
                if not placed:
                    return None
            return assignment  # type: ignore[return-value]
        # PACK fallback / SPREAD: greedy, SPREAD rotates through nodes
        rotation = 0
        for i, b in enumerate(spec.bundles):
            placed = False
            candidates = order[rotation:] + order[:rotation] if spec.strategy == "SPREAD" else order
            for nid in candidates:
                if fits(nid, b):
                    assignment[i] = nid
                    for k, v in b.resources.items():
                        scratch[nid][k] = scratch[nid].get(k, 0.0) - v
                    placed = True
                    if spec.strategy == "SPREAD":
                        rotation = (order.index(nid) + 1) % len(order)
                    break
            if not placed:
                return None
        return assignment  # type: ignore[return-value]

    async def _schedule_pg(self, pg: PGRecord):
        """2PC: prepare (reserve) on every node, then commit; cancel on any
        failure (reference: gcs_placement_group_scheduler.h:115-118)."""
        while pg.state in ("PENDING", "RESCHEDULING"):
            plan = self._plan_pg(pg)
            if plan is None:
                # surface each bundle to the autoscaler (PACK/SPREAD gangs
                # scale up via ordinary shape demand; STRICT_SPREAD is also
                # exported whole so distinct-node needs are visible)
                for idx, b in enumerate(pg.spec.bundles):
                    self._record_demand(
                        b.resources, b.label_selector,
                        waiter_id=f"{pg.spec.pg_id.hex()}:{idx}")
                await asyncio.sleep(0.5)
                continue
            per_node: Dict[NodeID, List[int]] = {}
            for idx, nid in enumerate(plan):
                per_node.setdefault(nid, []).append(idx)
            prepared: List[NodeID] = []
            ok = True
            for nid, idxs in per_node.items():
                try:
                    reply = wire.loads(await self.node_clients[nid].call(
                        "PreparePGBundles", wire.dumps({
                            "pg_id": pg.spec.pg_id.binary(),
                            "bundles": {i: pg.spec.bundles[i].resources for i in idxs},
                        }), timeout=10.0))
                    if reply.get("status") != "ok":
                        ok = False
                        break
                    prepared.append(nid)
                except (RpcError, asyncio.TimeoutError, OSError):
                    ok = False
                    break
            if not ok:
                # release EVERY attempted node, not just acked ones: a
                # prepare that timed out may still have applied on the
                # raylet (releasing an unprepared pg is a no-op)
                for nid in per_node:
                    try:
                        await self.node_clients[nid].call("ReleasePGBundles", wire.dumps(
                            {"pg_id": pg.spec.pg_id.binary()}), timeout=10.0, retries=1)
                    except (RpcError, asyncio.TimeoutError, OSError) as e:
                        logger.debug("ReleasePGBundles pg=%s to %s failed: %s",
                                     pg.spec.pg_id.hex()[:8], nid.hex()[:8], e)
                await asyncio.sleep(0.3)
                continue
            for nid in per_node:
                try:
                    await self.node_clients[nid].call("CommitPGBundles", wire.dumps(
                        {"pg_id": pg.spec.pg_id.binary()}), timeout=10.0)
                except (RpcError, asyncio.TimeoutError, OSError) as e:
                    logger.debug("CommitPGBundles pg=%s to %s failed: %s",
                                 pg.spec.pg_id.hex()[:8], nid.hex()[:8], e)
            pg.bundle_nodes = list(plan)
            pg.state = "CREATED"
            self._persist_pg(pg)
            pg.ready_event.set()
            self._publish("pgs", {"event": "created", "pg_id": pg.spec.pg_id.hex()})
            return

    # ------------------------------------------------------------------
    # autoscaler support (reference: gcs_autoscaler_state_manager.cc)
    # ------------------------------------------------------------------

    def _record_demand(self, resources: Dict[str, float], selector: Dict[str, str],
                       waiter_id: str = ""):
        """Count DISTINCT waiters per shape (a task retrying PickNode every
        0.5s is one unit of demand, not one per retry)."""
        now = time.monotonic()
        key = (tuple(sorted(resources.items())), tuple(sorted(selector.items())))
        entry = self.pending_demands.get(key)
        if entry is None:
            entry = self.pending_demands[key] = {
                "shape": dict(resources), "selector": dict(selector),
                "waiters": {}, "last_ts": now}
        entry["waiters"][waiter_id or "_anon"] = now
        entry["last_ts"] = now
        self._prune_demands(now)

    def _prune_demands(self, now: float):
        ttl = RAY_CONFIG.autoscaler_demand_ttl_s
        for key in [k for k, v in self.pending_demands.items()
                    if now - v["last_ts"] > ttl]:
            del self.pending_demands[key]
        for v in self.pending_demands.values():
            stale = [w for w, ts in v["waiters"].items() if now - ts > ttl]
            for w in stale:
                del v["waiters"][w]

    def _node_used(self, node_id: NodeID) -> bool:
        """A node is in use if any resource is claimed OR any lease is held
        (zero-resource actors must not look idle to the autoscaler)."""
        info = self.nodes.get(node_id)
        if info is None:
            return False
        avail = self.node_available.get(node_id)
        if avail is None:
            return True  # no view yet: err on the busy side
        if any(avail.get(k, 0.0) < v - 1e-9
               for k, v in info.total_resources.items()):
            return True
        return self.node_num_leases.get(node_id, 0) > 0

    async def _rpc_GetClusterStatus(self, req, conn):
        """Everything the autoscaler reconciler needs in one poll: per-node
        resources + idle info and the unplaceable-demand shapes."""
        now = time.monotonic()
        self._prune_demands(now)
        nodes = []
        for nid, info in self.nodes.items():
            nodes.append({
                "node_id": nid.hex(),
                "alive": info.alive,
                "is_head": info.is_head,
                "labels": dict(info.labels),
                "total": dict(info.total_resources),
                "available": dict(self.node_available.get(nid, {})),
                "used": self._node_used(nid),
                "idle_s": now - self.node_last_used.get(nid, now),
            })
        demands = [
            {"shape": v["shape"], "selector": v["selector"],
             "count": min(len(v["waiters"]), 64)}
            for v in self.pending_demands.values() if v["waiters"]
        ]
        strict_spread = [
            [dict(b.resources) for b in pg.spec.bundles]
            for pg in self.pgs.values()
            if pg.state in ("PENDING", "RESCHEDULING")
            and pg.spec.strategy == "STRICT_SPREAD"
        ]
        return {"nodes": nodes, "demands": demands, "strict_spread": strict_spread}

    # ------------------------------------------------------------------
    # cluster health plane: metrics history + stuck/straggler monitor
    # ------------------------------------------------------------------

    async def _metrics_history_loop(self):
        """Sample the aggregated metric snapshots into the raw history
        ring every ``metrics_history_interval_s`` (the rollup tier fires
        from inside :meth:`MetricsHistory.sample`)."""
        interval = RAY_CONFIG.metrics_history_interval_s
        while True:
            await asyncio.sleep(interval)
            try:
                self.metrics_history.sample()
            except Exception:
                logger.exception("metrics-history sample failed")

    async def _rpc_GetMetricsHistory(self, req, conn):
        name = req.get("name")
        if not name:
            return {"names": self.metrics_history.names()}
        return {"history": self.metrics_history.series(
            name, window_s=req.get("window_s"),
            tier=req.get("tier") or "auto")}

    async def _ckpt_sweep_loop(self):
        """Cluster-side checkpoint retention (reference analog: the GCS
        owning GC instead of each driver): periodically sweep every
        checkpoint store whose KV stats mirror carries a ``sweep``
        policy. The filesystem/backend work runs off-loop in the default
        executor — a slow tier must not stall the control plane."""
        interval = RAY_CONFIG.ckpt_sweep_interval_s
        if not interval:
            return
        while True:
            await asyncio.sleep(interval)
            try:
                await self._ckpt_sweep()
            except Exception:
                logger.exception("ckpt retention sweep failed")

    async def _ckpt_sweep(self) -> list:
        """One cluster-wide retention pass over opted-in stores. Reports
        land in KV ns="ckpt_sweep" (state API / dashboard) and reap
        activity becomes ``ckpt_sweeper`` events."""
        entries = {}
        for (ns, key), blob in list(self.kv.items()):
            if ns != "ckpt":
                continue
            try:
                entries[key] = wire.loads(blob)
            except Exception:
                logger.debug("ckpt sweep: undecodable stats mirror for "
                             "store %r; skipping", key)
                continue
        if not entries:
            return []
        from ray_tpu.ckpt.tier.sweeper import sweep_registered

        loop = asyncio.get_running_loop()
        reports = await loop.run_in_executor(None, sweep_registered, entries)
        for rep in reports:
            name = str(rep.get("name") or rep.get("root") or "?")
            blob = wire.dumps(rep)
            self.kv[("ckpt_sweep", name)] = blob
            self._persist_kv("ckpt_sweep", name, blob)
            if rep.get("error"):
                self._record_event(
                    "ckpt_sweeper", "WARNING",
                    f"retention sweep of store {name} failed: "
                    f"{rep['error']}", root=rep.get("root"))
            elif rep.get("dropped_manifests") or rep.get("dropped_bytes"):
                self._record_event(
                    "ckpt_sweeper", "INFO",
                    f"store {name}: reaped {rep['dropped_manifests']} "
                    f"manifests / {rep['dropped_bytes']} chunk bytes "
                    f"across tiers",
                    root=rep.get("root"), local=rep.get("local"),
                    remote=rep.get("remote"))
        return reports

    async def _rpc_CkptSweep(self, req, conn):
        """Force a cluster retention sweep now (tests, ``ray-tpu ckpt``)."""
        return {"reports": await self._ckpt_sweep()}

    async def _health_monitor_loop(self):
        interval = RAY_CONFIG.health_scan_interval_s
        while True:
            await asyncio.sleep(interval)
            try:
                await self._health_scan()
            except Exception:
                logger.exception("cluster health scan failed")

    async def _health_scan(self) -> dict:
        """One pass of the cluster health monitor: stuck tasks (RUNNING far
        past the per-function p99 of completed runs), straggler raylets
        (lease-queue / event-loop-lag outliers vs the cluster median, and
        lagging heartbeats), and provisioning-pool pathology (dead zygote,
        starved warm pool). The task scan runs on the task-event merge
        thread; findings surface via ``GetClusterHealth`` → ``/api/health``
        / ``util.state.cluster_health`` / ``ray-tpu health``, plus
        rate-limited warning logs."""
        now = time.time()
        cfg = RAY_CONFIG
        findings: List[dict] = []

        # -- stuck tasks ------------------------------------------------
        stuck_min = cfg.health_stuck_min_s
        stuck_factor = cfg.health_stuck_p99_factor
        stuck_fallback = cfg.health_stuck_fallback_s

        def _scan_stuck(tm) -> List[dict]:
            durations: Dict[str, List[float]] = {}
            running: List[Tuple[dict, float]] = []
            for rec in tm.list_tasks(limit=100_000):
                run_ts = next((e["ts"] for e in rec["events"]
                               if e["state"] == RUNNING), None)
                if run_ts is None:
                    continue
                if rec["state"] == "FINISHED":
                    durations.setdefault(rec["name"] or "?", []).append(
                        rec["end_ts"] - run_ts)
                elif rec["state"] == RUNNING:
                    running.append((rec, run_ts))
            out = []
            for rec, run_ts in running:
                fn = rec["name"] or "?"
                age = now - run_ts
                ds = sorted(durations.get(fn, ()))
                if ds:
                    p99 = ds[min(len(ds) - 1, int(0.99 * len(ds)))]
                    threshold = max(stuck_min, stuck_factor * p99)
                else:
                    p99 = None  # no completed sample yet: conservative
                    threshold = max(stuck_min, stuck_fallback)
                if age > threshold:
                    out.append({
                        "kind": "stuck_task", "severity": "warning",
                        "task_id": rec["task_id"], "name": fn,
                        "node": rec.get("node", ""),
                        "worker": rec.get("worker", ""),
                        "age_s": age, "threshold_s": threshold,
                        "p99_s": p99})
            return out

        findings.extend(await self.task_manager.read(_scan_stuck))

        # -- straggler raylets ------------------------------------------
        for metric, floor in (("ray_tpu_raylet_lease_queue_depth", 4.0),
                              ("ray_tpu_raylet_loop_lag_seconds", 0.2)):
            by_node = self.metrics_history.latest_by_node(metric)
            if len(by_node) < 2:
                continue
            vals = sorted(by_node.values())
            median = vals[len(vals) // 2]
            for node, v in by_node.items():
                if v > floor and v > cfg.health_straggler_factor * max(
                        median, 1e-9):
                    findings.append({
                        "kind": "straggler_node", "severity": "warning",
                        "node": node, "metric": metric, "value": v,
                        "cluster_median": median})
        timeout = RAY_CONFIG.health_check_timeout_ms / 1000.0
        mono = time.monotonic()
        for node_id, info in self.nodes.items():
            if not info.alive:
                continue
            lag = mono - self.node_last_seen.get(node_id, mono)
            if lag > timeout / 2:  # lagging but not yet declared dead
                findings.append({
                    "kind": "straggler_node", "severity": "warning",
                    "node": node_id.hex()[:16], "metric": "heartbeat_lag_s",
                    "value": lag, "cluster_median": 0.0})

        # -- provisioning pools -----------------------------------------
        for (ns, key), blob in list(self.kv.items()):
            if ns != "workers" or not blob:
                continue
            try:
                entry = wire.loads(blob)
            except Exception as e:
                logger.debug("undecodable workers entry %s: %s", key, e)
                continue
            pool = entry.get("pool") or {}
            node = str(entry.get("node", key))[:16]
            if pool.get("enabled") and not pool.get("zygote_alive"):
                findings.append({
                    "kind": "dead_zygote", "severity": "error",
                    "node": node,
                    "zygote_restarts": pool.get("zygote_restarts", 0)})
            elif (pool.get("warm_target", 0) > 0
                    and pool.get("warm_default_env", 0) == 0):
                findings.append({
                    "kind": "pool_starvation", "severity": "warning",
                    "node": node,
                    "warm_target": pool.get("warm_target", 0),
                    "misses": pool.get("misses", 0)})

        # -- serve SLOs -------------------------------------------------
        # the serve controller mirrors per-deployment autoscale state into
        # the ``serve`` KV namespace; deployments that registered SLO
        # targets get violation findings when the windowed rates breach
        for (ns, key), blob in list(self.kv.items()):
            if ns != "serve" or not blob:
                continue
            try:
                entry = wire.loads(blob)
            except Exception as e:
                logger.debug("undecodable serve entry %s: %s", key, e)
                continue
            slo = entry.get("slo") or {}
            rollup = entry.get("rollup") or {}
            dep = key.decode() if isinstance(key, bytes) else str(key)
            if entry.get("ts") and now - entry["ts"] > 60.0:
                continue  # stale mirror (controller gone): not a violation
            queue_target = slo.get("queue_target_s")
            queue_p99 = rollup.get("queue_p99_s")
            if (queue_target is not None and queue_p99 is not None
                    and queue_p99 > queue_target):
                findings.append({
                    "kind": "serve_slo_violation", "severity": "warning",
                    "deployment": dep, "metric": "queue_p99_s",
                    "value": queue_p99, "target": queue_target,
                    "replicas": entry.get("replicas"),
                    "replica_target": entry.get("target")})
            latency_budget = slo.get("latency_budget_s")
            exec_mean = rollup.get("execute_mean_s")
            if (latency_budget is not None and exec_mean is not None
                    and exec_mean > latency_budget):
                findings.append({
                    "kind": "serve_slo_violation", "severity": "warning",
                    "deployment": dep, "metric": "execute_mean_s",
                    "value": exec_mean, "target": latency_budget,
                    "replicas": entry.get("replicas"),
                    "replica_target": entry.get("target")})
            ttft_target = slo.get("ttft_target_s")
            ttft_p99 = rollup.get("ttft_p99_s")
            if (ttft_target is not None and ttft_p99 is not None
                    and ttft_p99 > ttft_target):
                findings.append({
                    "kind": "serve_slo_violation", "severity": "warning",
                    "deployment": dep, "metric": "ttft_p99_s",
                    "value": ttft_p99, "target": ttft_target,
                    "replicas": entry.get("replicas"),
                    "replica_target": entry.get("target")})

        # -- goodput ledger ---------------------------------------------
        # per-job wall-clock attribution pathologies: recompile storms,
        # input-bound steps, over-budget checkpoint pauses, and goodput
        # regression vs the job's own trailing window
        findings.extend(self.goodput_ledger.findings(now, cfg))

        status = "ok"
        if any(f["severity"] == "error" for f in findings):
            status = "error"
        elif findings:
            status = "warning"
        self._health = {
            "ts": now, "status": status, "findings": findings,
            "scan_count": self._health.get("scan_count", 0) + 1,
            "scan_interval_s": cfg.health_scan_interval_s,
            "nodes_alive": sum(1 for n in self.nodes.values() if n.alive),
        }
        # rate-limited warning logs + structured events (one per finding
        # identity per health_warn_interval_s, not one per scan)
        for f in findings:
            ident = (f["kind"], f.get("node", ""), f.get("task_id", ""),
                     f.get("deployment", ""), f.get("metric", ""),
                     f.get("job", ""))
            if now - self._health_warn_ts.get(ident, 0.0) \
                    < cfg.health_warn_interval_s:
                continue
            self._health_warn_ts[ident] = now
            detail = {k: v for k, v in f.items()
                      if k not in ("kind", "severity")}
            logger.warning("cluster health: %s %s", f["kind"], detail)
            self._record_event("health", f["severity"].upper(),
                               f"health finding: {f['kind']}", **detail)
        if len(self._health_warn_ts) > 10_000:  # bounded dedup memory
            cutoff = now - cfg.health_warn_interval_s
            self._health_warn_ts = {k: ts for k, ts
                                    in self._health_warn_ts.items()
                                    if ts >= cutoff}
        return self._health

    async def _rpc_GetClusterHealth(self, req, conn):
        if req.get("scan") or not self._health.get("scan_count"):
            await self._health_scan()
        return {"health": self._health}

    async def _rpc_GetGoodput(self, req, conn):
        """Per-job goodput ledgers (``/api/goodput`` /
        ``util.state.goodput()`` / ``ray-tpu goodput``)."""
        jobs = self.goodput_ledger.jobs()
        job = req.get("job")
        if job:
            jobs = {job: jobs[job]} if job in jobs else {}
        return {"jobs": jobs}

    # ------------------------------------------------------------------
    # debug / state api
    # ------------------------------------------------------------------

    async def _rpc_GetState(self, req, conn):
        return {
            "nodes": [n.to_dict() for n in self.nodes.values()],
            "actors": [r.info() for r in self.actors.values()],
            "jobs": list(self.jobs.values()),
            "num_objects_tracked": len(self.object_dir),
            "pgs": [
                {"pg_id": p.spec.pg_id.hex(), "state": p.state, "name": p.spec.name}
                for p in self.pgs.values()
            ],
            "uptime_s": time.time() - self.start_time,
        }


def main():
    from ray_tpu._private.common import die_with_parent

    die_with_parent()

    import argparse

    from ray_tpu._private.logs import setup_process_logging

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--address-file", required=True)
    parser.add_argument("--log-dir", default="")
    parser.add_argument("--persist-dir", default="",
                        help="durable store directory enabling GCS fault tolerance")
    args = parser.parse_args()
    setup_process_logging("gcs", args.log_dir)

    async def run():
        gcs = GcsServer(args.host, args.port, persist_dir=args.persist_dir)
        addr = await gcs.start()
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(addr)
        import os as _os

        _os.replace(tmp, args.address_file)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()

"""Object serialization: cloudpickle + out-of-band zero-copy buffers.

Equivalent of the reference's ``python/ray/_private/serialization.py``:
values are pickled with protocol 5 and large contiguous buffers (numpy / jax
arrays) are captured out-of-band so they can live in shared memory and be
mapped zero-copy by readers.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List, Tuple

import cloudpickle

# Payloads >= this many bytes are pulled out-of-band; below it, inline pickling
# is cheaper than a separate buffer round trip.
_OOB_THRESHOLD = 1024


def _to_picklable(value: Any) -> Any:
    """Convert device arrays (jax) to host numpy without importing jax eagerly."""
    t = type(value)
    mod = t.__module__
    if mod.startswith("jaxlib") or mod.startswith("jax"):
        import numpy as np

        try:
            return np.asarray(value)
        except Exception:
            return value
    return value


def serialize(value: Any) -> Tuple[bytes, List[memoryview]]:
    """Returns (inband_bytes, out_of_band_buffers)."""
    buffers: List[memoryview] = []

    def buffer_cb(pickle_buffer):
        mv = pickle_buffer.raw()
        if mv.nbytes >= _OOB_THRESHOLD:
            buffers.append(mv)
            return False  # out of band
        return True  # keep in band

    value = _to_picklable(value)
    inband = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_cb)
    return inband, buffers


def deserialize(inband: bytes, buffers: List[Any]) -> Any:
    return pickle.loads(inband, buffers=[pickle.PickleBuffer(b) for b in buffers])


def loads_trusted(blob: bytes) -> Any:
    """Unpickle a blob whose PRODUCER is trusted: client-proxy payloads, or
    function/params blobs authored by the deploying driver.

    Unpickling EXECUTES code from the blob, so this module is the single
    audited chokepoint for it (enforced by raylint rule SER001). Calling this
    is an explicit declaration that the bytes come from inside the cluster
    trust boundary — e.g. the client-proxy port, which therefore must never
    be exposed to untrusted networks (it has no authentication of its own).
    Anything that must be safe against arbitrary senders goes through the
    typed schema in ``wire.py`` instead, which never unpickles. If you are
    about to call ``pickle.loads``/``cloudpickle.loads`` anywhere else, call
    this instead — or better, ask whether the payload can be a wire-typed
    message.
    """
    return cloudpickle.loads(blob)


def dumps_oob(value: Any) -> bytes:
    """Single-blob serialization: [u32 nbuf][u64 len, bytes]* [inband]."""
    inband, buffers = serialize(value)
    out = io.BytesIO()
    out.write(len(buffers).to_bytes(4, "big"))
    for b in buffers:
        out.write(b.nbytes.to_bytes(8, "big"))
        out.write(b)
    out.write(inband)
    return out.getvalue()


def loads_oob(blob: bytes) -> Any:
    view = memoryview(blob)
    nbuf = int.from_bytes(view[:4], "big")
    off = 4
    buffers = []
    for _ in range(nbuf):
        n = int.from_bytes(view[off : off + 8], "big")
        off += 8
        buffers.append(view[off : off + n])
        off += n
    return deserialize(bytes(view[off:]), buffers)

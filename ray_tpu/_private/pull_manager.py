"""Prioritized pull admission (reference: object_manager/pull_manager.cc).

The reference classes pulls by urgency — a blocked ``ray.get`` outranks task
argument fetches, which outrank background/wait prefetches — and cancels
pulls nobody needs anymore. This is the asyncio equivalent: a fixed number
of transfer slots, admission by (priority class, FIFO) order, priority
upgrades when a hotter requester arrives, and cancellation of queued pulls
whose waiters have all gone away.

Priorities: 0 = get (a caller is blocked on the value NOW),
1 = task-arg (a leased task is waiting to start), 2 = background
(broadcast prefetch / wait warm-up).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from typing import Dict, Optional

PRIO_GET = 0
PRIO_ARG = 1
PRIO_BACKGROUND = 2


class PullQueue:
    def __init__(self, slots: int, stale_ttl_s: float = 30.0):
        self._slots = max(1, slots)
        self._in_flight = 0
        self._stale_ttl = stale_ttl_s
        self._seq = itertools.count()
        # oid -> entry; entry: prio, seq, queued_at, waiters, event, state
        self._entries: Dict[bytes, dict] = {}

    # -- waiter interest (drives obsolete-pull cancellation) ----------

    def add_waiter(self, oid: bytes):
        e = self._entries.get(oid)
        if e is not None:
            e["waiters"] += 1

    def remove_waiter(self, oid: bytes):
        e = self._entries.get(oid)
        if e is not None and e["waiters"] > 0:
            e["waiters"] -= 1

    # -- admission -----------------------------------------------------

    def request(self, oid: bytes, prio: int) -> None:
        """Register (or upgrade) a pull's priority before admit()."""
        e = self._entries.get(oid)
        if e is None:
            # waiters starts at 0: interest is asserted only by
            # add_waiter() (the StoreGet path), so a pull whose every
            # getter left really does hit the <= 0 stale sweep
            self._entries[oid] = {
                "prio": prio, "seq": next(self._seq),
                "queued_at": time.monotonic(), "waiters": 0,
                "event": asyncio.Event(), "state": "queued"}
        elif prio < e["prio"]:
            e["prio"] = prio  # upgrade keeps the original FIFO seq
            self._kick()

    async def admit(self, oid: bytes) -> bool:
        """Wait for a transfer slot. Returns False if the pull was
        cancelled as obsolete while queued. Only pulls parked HERE compete
        for slots — a pull still polling the directory for locations must
        not hold up admissible transfers behind it."""
        e = self._entries.get(oid)
        if e is None:
            self.request(oid, PRIO_BACKGROUND)
            e = self._entries[oid]
        if e["state"] == "queued":
            e["state"] = "ready"
        while True:
            if e["state"] == "cancelled":
                self._entries.pop(oid, None)
                return False
            if e["state"] == "ready" and self._in_flight < self._slots \
                    and self._next_oid() == oid:
                e["state"] = "transferring"
                self._in_flight += 1
                return True
            e["event"].clear()
            try:
                await asyncio.wait_for(e["event"].wait(), 0.5)
            except asyncio.TimeoutError:
                self._sweep_stale()

    def release(self, oid: bytes):
        e = self._entries.pop(oid, None)
        if e is not None and e["state"] == "transferring":
            # raylint: disable=RCE001 release() is only called from the raylet's async pull path (same loop as admit); the cross-object call edge is beyond the resolver, so its context defaults to the caller thread
            self._in_flight -= 1
        self._kick()

    def cancel(self, oid: bytes):
        e = self._entries.get(oid)
        if e is not None and e["state"] in ("queued", "ready"):
            e["state"] = "cancelled"
            e["event"].set()

    # -- internals -----------------------------------------------------

    def _next_oid(self) -> Optional[bytes]:
        best = None
        for oid, e in self._entries.items():
            if e["state"] != "ready":
                continue
            key = (e["prio"], e["seq"])
            if best is None or key < best[0]:
                best = (key, oid)
        return best[1] if best else None

    def _kick(self):
        for e in self._entries.values():
            if e["state"] in ("queued", "ready"):
                e["event"].set()

    def _sweep_stale(self):
        """Cancel queued pulls whose waiters all left (reference:
        pull_manager.cc deactivating pulls no request needs)."""
        now = time.monotonic()
        for oid, e in list(self._entries.items()):
            if e["state"] in ("queued", "ready") and e["waiters"] <= 0 \
                    and now - e["queued_at"] > self._stale_ttl:
                self.cancel(oid)

    def stats(self) -> dict:
        by_prio: Dict[int, int] = {}
        for e in self._entries.values():
            if e["state"] in ("queued", "ready"):
                by_prio[e["prio"]] = by_prio.get(e["prio"], 0) + 1
        return {"in_flight": self._in_flight, "queued_by_prio": by_prio,
                "total_tracked": len(self._entries)}

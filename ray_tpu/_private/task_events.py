"""Task lifecycle event pipeline: the per-process event buffer.

Reference: ``src/ray/core_worker/task_event_buffer.cc`` — every core worker
buffers per-task state transitions (status events + profile events) and
periodically flushes them in batches to the GCS ``GcsTaskManager``
(``gcs/gcs_server/gcs_task_manager.cc``), which keeps a bounded per-job
store powering ``ray summary tasks``, ``ray list tasks`` and the dashboard
timeline.

Here: both sides of a task record timestamped transitions into this
module's bounded buffer — the OWNER records SUBMITTED / LEASE_REQUESTED /
SCHEDULED / RETRYING / FINISHED / FAILED, the EXECUTING worker records
RUNNING — and the core worker's observability flush loop ships batches to
the GCS ``AddTaskEvents`` RPC (``_private/gcs.py``), where the
GcsTaskManager-equivalent merges them per task id. Surfaced via
``util.state.list_tasks()/get_task()/summarize_tasks()``, the dashboard's
``/api/tasks``, and the ``ray-tpu tasks`` CLI.

Always on by default (like the reference's task events): recording is a
lock + list append; set ``RAY_TPU_TASK_EVENTS=0`` to disable entirely.
The buffer is bounded (drop-oldest + drop counter, mirrored to the GCS so
truncation is visible, never silent).
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# Lifecycle states, in nominal order (reference: common.proto TaskStatus).
SUBMITTED = "SUBMITTED"
LEASE_REQUESTED = "LEASE_REQUESTED"
SCHEDULED = "SCHEDULED"
RUNNING = "RUNNING"
RETRYING = "RETRYING"
FINISHED = "FINISHED"
FAILED = "FAILED"

TERMINAL_STATES = (FINISHED, FAILED)

_MAX_BUFFER = 10_000  # drop-oldest beyond this: events never leak unbounded
_ERR_MAX = 200  # error summaries are truncated; full tracebacks stay in logs

_lock = threading.Lock()
_buffer: "deque[dict]" = deque()
_dropped = 0
_enabled: Optional[bool] = None


def enabled() -> bool:
    # double-checked under _lock: the unlocked fast path never writes, so
    # a racing set_enabled() override cannot be overwritten by a stale
    # env read (the old unlocked check-then-act lost exactly that update)
    global _enabled
    if _enabled is None:
        with _lock:
            if _enabled is None:
                _enabled = os.environ.get(
                    "RAY_TPU_TASK_EVENTS", "1") not in ("0", "false")
    return _enabled


def set_enabled(value: Optional[bool]):
    """Override the env flag (None = re-read it); used by tests/benchmarks."""
    global _enabled
    with _lock:
        _enabled = value


def _append(entry) -> None:
    """Bounded drop-oldest append — the single buffer-management path for
    both dict events and SUBMITTED slab tuples."""
    global _dropped
    with _lock:
        if len(_buffer) >= _MAX_BUFFER:
            _buffer.popleft()
            _dropped += 1
        _buffer.append(entry)


def _base_event(task_id_hex: str, state: str, ts: float, attempt: int,
                name: str, job_id: str, span_id: str, parent_span: str,
                arg_bytes: int) -> Dict[str, Any]:
    """The field-elision ladder shared by :func:`record` and the slab
    expansion — one source of truth for the event shape."""
    event: Dict[str, Any] = {"task_id": task_id_hex, "state": state,
                             "ts": ts, "attempt": attempt}
    if name:
        event["name"] = name
    if job_id:
        event["job_id"] = job_id
    if span_id:
        event["span_id"] = span_id
    if parent_span:
        event["parent_span"] = parent_span
    if arg_bytes:
        event["arg_bytes"] = int(arg_bytes)
    return event


def record_submitted(task_id_hex: str, ts: float, name: str, job_id: str,
                     arg_bytes: int, span_id: str = "",
                     parent_span: str = "") -> None:
    """Slab append for the owner's SUBMITTED record — the one lifecycle
    event that rides the ``.remote()`` hot loop. Appends a bare tuple;
    :func:`drain` expands it into the normal event dict off the hot path
    (flush time), so a 20k-task burst pays tuple-pack + append per task
    instead of an 8-key dict construction."""
    if not enabled():
        return
    _append((task_id_hex, ts, name, job_id, arg_bytes, span_id, parent_span))


def _expand_submitted(slab: tuple) -> dict:
    task_id_hex, ts, name, job_id, arg_bytes, span_id, parent_span = slab
    return _base_event(task_id_hex, SUBMITTED, ts, 0, name, job_id,
                       span_id, parent_span, arg_bytes)


def record(task_id_hex: str, state: str, *, name: str = "", job_id: str = "",
           attempt: int = 0, error: str = "", worker: str = "",
           node: str = "", arg_bytes: int = 0, ret_bytes: int = 0,
           span_id: str = "", parent_span: str = "") -> None:
    """Buffer one state transition. Cheap (lock + append); never raises.

    ``arg_bytes`` rides the owner's SUBMITTED event (serialized argument
    payload size), ``ret_bytes`` the terminal FINISHED event (serialized
    return payload size, inline or store-resident) — the per-task object
    accounting surfaced by ``summarize_tasks``. ``span_id`` is the task's
    deterministic execution-span id and ``parent_span`` the submitter's
    active span: the GCS timeline endpoint joins them across task records
    to draw parent→child flow arrows without needing the span table."""
    if not enabled():
        return
    event = _base_event(task_id_hex, state, time.time(), attempt,
                        name, job_id, span_id, parent_span, arg_bytes)
    if ret_bytes:
        event["ret_bytes"] = int(ret_bytes)
    if error:
        # summary, not transcript: first line, bounded (full tracebacks
        # stay in worker logs / the task's error object)
        event["error"] = error.splitlines()[0][:_ERR_MAX]
    if worker:
        event["worker"] = worker
    if node:
        event["node"] = node
    _append(event)


def drain() -> Tuple[List[dict], int]:
    """Take everything buffered (called by the flush loop). Returns
    (events, dropped_since_last_drain)."""
    global _dropped
    with _lock:
        if not _buffer and not _dropped:
            return [], 0
        events, dropped = list(_buffer), _dropped
        _buffer.clear()
        _dropped = 0
    # slab entries (SUBMITTED hot path) expand here, off the submit loop
    return [_expand_submitted(e) if type(e) is tuple else e
            for e in events], dropped


def rebuffer(events: List[dict], dropped: int = 0):
    """Put events (and the drained drop count) back after a failed flush
    (oldest-first, still bounded) — a failed ship must not erase the
    truncation evidence the counter exists to surface."""
    global _dropped
    with _lock:
        _dropped += dropped
        _buffer.extendleft(reversed(events))
        while len(_buffer) > _MAX_BUFFER:
            _buffer.popleft()
            _dropped += 1


def pending() -> int:
    with _lock:
        return len(_buffer)


def reset_after_fork():
    """Drop the buffer a forked child inherited from its parent's image.
    Without this a zygote-forked worker re-ships the zygote process's
    buffered transitions (and their drop counter) to the GCS on its first
    flush, duplicating records the parent already owns."""
    global _dropped, _enabled
    with _lock:
        _buffer.clear()
        _dropped = 0
    _enabled = None  # re-read the env in the child (runtime env may differ)


def flush():
    """Synchronously push buffered events to the GCS; safe to call anywhere
    (worker shutdown, atexit). Mirrors tracing.flush()'s tiering: no-op
    pre-init and in local mode; from the worker's own event loop it ships
    fire-and-forget (blocking there would deadlock the loop)."""
    events, dropped = drain()
    if not events and not dropped:
        return
    try:
        from ray_tpu._private.worker import global_worker, is_initialized

        if not is_initialized():
            rebuffer(events, dropped)
            return
        core = global_worker()
        if getattr(core, "mode", "") == "local" or not hasattr(core, "_gcs_call"):
            return  # local mode: lifecycle is inline; nothing to ship
        req = {"events": events, "dropped": dropped}

        async def _put_guarded():
            try:
                await core._gcs_call("AddTaskEvents", req)
            except Exception:
                rebuffer(events, dropped)

        import asyncio

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is not None and running is core.loop:
            from ray_tpu._private.async_util import spawn

            spawn(_put_guarded(), what="task-event flush")
        else:
            core._run(_put_guarded())
    except Exception:
        # observability must never take down the workload
        rebuffer(events, dropped)


# tail-event protection: transitions recorded in the last flush interval
# before process exit must not die with the process (tracing.py registers
# the same hook for spans on first record)
atexit.register(flush)

"""Env-overridable configuration registry.

Equivalent of the reference's ``RAY_CONFIG`` macro table
(``src/ray/common/ray_config_def.h``): every knob has a typed default and can be
overridden per-process with ``RAY_TPU_<NAME>`` environment variables, so the
whole cluster (GCS, raylets, workers) shares one config surface.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

_ENV_PREFIX = "RAY_TPU_"


def _coerce(value: str, default: Any) -> Any:
    if isinstance(default, bool):
        return value.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(value)
    if isinstance(default, float):
        return float(value)
    if isinstance(default, (dict, list)):
        return json.loads(value)
    return value


class _ConfigRegistry:
    """Typed config table; attribute access returns the (env-overridden) value."""

    _defs: Dict[str, Any] = {}

    def define(self, name: str, default: Any, doc: str = "") -> None:
        self._defs[name] = default

    def __getattr__(self, name: str) -> Any:
        try:
            default = self._defs[name]
        except KeyError:
            raise AttributeError(f"unknown config {name!r}")
        env = os.environ.get(_ENV_PREFIX + name.upper())
        if env is not None:
            return _coerce(env, default)
        return default

    def items(self):
        return {k: getattr(self, k) for k in self._defs}.items()


RAY_CONFIG = _ConfigRegistry()
_d = RAY_CONFIG.define

# --- networking / rpc ---
_d("rpc_connect_timeout_s", 10.0)
_d("rpc_call_timeout_s", 60.0)
_d("rpc_retry_base_delay_ms", 50)
_d("rpc_retry_max_delay_ms", 2000)
_d("rpc_max_retries", 5)
# ceiling on blind reconnect+retry of calls that provably never reached the
# peer (safe for non-idempotent calls); keeps dead-peer detection fast
_d("rpc_presend_retry_timeout_s", 15.0)
# after a GCS restart, how often to poll a replayed RUNNING job's driver
# before declaring it gone and reaping the job's actors
_d("gcs_driver_reattach_grace_s", 10.0)
# unplaceable-demand entries older than this drop out of the autoscaler view
# (live demand refreshes itself via scheduling retries)
_d("autoscaler_demand_ttl_s", 15.0)
# Chaos injection (reference: src/ray/rpc/rpc_chaos.h). Format:
#   "Method=N" -> fail the first N calls of Method;
#   "Method=N:p" -> after the first N, fail with probability p.
_d("testing_rpc_failure", "")
_d("testing_rpc_reply_failure", "")  # handler runs, reply dropped (zombies)
_d("testing_rpc_delay_ms", 0)

# --- GCS / control plane ---
_d("gcs_port", 0)  # 0 -> pick a free port
_d("health_check_period_ms", 1000)
_d("health_check_timeout_ms", 5000)
_d("gcs_storage", "memory")  # "memory" | "file"
_d("pubsub_max_buffered", 4096)

# --- raylet / scheduling ---
_d("worker_pool_prestart", 0)
_d("worker_idle_timeout_s", 300.0)
_d("max_workers_per_node", 64)
_d("lease_spillback_max_hops", 4)
# smallest total argument footprint that makes locality steer lease placement
_d("locality_min_arg_bytes", 64 * 1024)
# queued pulls with no remaining waiters are cancelled after this long
_d("object_pull_interest_ttl_s", 30.0)
_d("scheduler_spread_threshold", 0.5)  # hybrid policy: pack below, spread above
_d("worker_start_timeout_s", 60.0)
# how long a task waits for a feasible node (an autoscaler may add one)
# before failing with a scheduling error
_d("infeasible_task_timeout_s", 300.0)

_d("object_pull_concurrency", 8)  # concurrent inbound transfers per node

# --- OOM defense (reference: memory_monitor.h:52) ---
_d("memory_usage_threshold", 0.95)
_d("memory_monitor_refresh_ms", 500)
# 0 = node-level /proc/meminfo accounting; >0 = budget over worker RSS
_d("memory_monitor_capacity_bytes", 0)

# --- object store ---
_d("object_store_memory", 2 * 1024**3)
_d("object_inline_max_bytes", 100 * 1024)
_d("object_chunk_bytes", 8 * 1024**2)
_d("object_spill_dir", "")  # default: <session>/spill
# spill backend: "" / "filesystem" | "s3://bucket/prefix" | "module:Class"
_d("object_spill_storage", "")
_d("object_pull_timeout_s", 120.0)
_d("object_store_backend", "auto")  # "auto" | "cpp" | "shm"
# pre-touch this much of the arena at start: first-touch page faults on
# /dev/shm cost ~65ms per 10MB on some hosts vs ~1ms warm
_d("object_store_prewarm_bytes", 256 * 1024**2)

# --- tasks / actors ---
_d("task_max_retries", 3)
_d("actor_max_restarts", 0)
_d("max_pending_lease_requests", 16)
_d("worker_startup_concurrency", 2)  # concurrent cold worker spawns per node
_d("prestart_workers", 2)  # idle workers spawned at raylet start

# --- worker provisioning plane (reference: worker_pool.h prestart/adoption) ---
# zygote prefork pool: a per-raylet zygote process pre-imports the heavy
# stack once and forks ready workers on demand; lease grants ADOPT a warm
# worker instead of paying a cold interpreter+import start-up
_d("worker_zygote_enabled", True)
_d("zygote_preimport_jax", False)  # pre-import jax in the zygote (threads!)
_d("zygote_fork_timeout_s", 20.0)
# warm default-runtime-env workers the replenish loop keeps forked AND
# registered so a lease grant is pure adoption (0 disables replenish; the
# one-shot prestart above still applies)
_d("worker_pool_warm_target", 2)
# multi-grant leases: one RequestWorkerLease can return up to this many
# grants when the owner asks (count=N); warm workers are granted first and
# the remainder is forked from the zygote (spawn-backed top-up)
_d("lease_max_grants", 8)
# renv-keyed warm pool: also keep this many warm workers forked for the
# most-recently-leased non-default runtime env (0 disables; hot renvs then
# always pay a fork on grant)
_d("worker_pool_warm_target_renv", 2)
# GCS resource_view coalescing tick: availability changes are folded into
# one batched publish per tick (membership changes still flush immediately)
_d("gcs_resource_view_tick_s", 0.1)
_d("max_lineage_bytes", 64 * 1024**2)
# ownership-based distributed refcounting (reference: reference_counter.h:44)
_d("distributed_refcounting", 1)
_d("free_grace_s", 1.0)  # settle delay before a zero-ref free (in-flight borrows)
_d("gcs_freed_tombstone_cap", 200000)  # bounded freed-object tombstone ring
# sustained unreachability before an owner declares a borrower dead and
# reclaims its borrows; borrowers re-assert every 30s, so partitions shorter
# than this are fully safe and longer ones only lose non-reconstructable data
_d("borrower_death_timeout_s", 120.0)
_d("borrow_debounce_s", 0.25)  # skip borrow RPCs for transient handles
_d("max_object_reconstructions", 5)

# --- observability (task events + metrics; reference: task_event_buffer.cc
# report interval + gcs_task_manager.cc per-job caps) ---
_d("task_events_flush_interval_s", 1.0)
_d("metrics_flush_interval_s", 10.0)
_d("gcs_task_events_max_per_job", 4096)  # per-job ring; drop-oldest beyond
_d("task_events_max_per_task", 64)  # transition entries kept per task
# sharded/pipelined GCS task-event ingestion: AddTaskEvents enqueues by
# task-id hash and returns; per-shard drain tasks merge in the background
_d("gcs_task_event_shards", 8)
_d("gcs_task_event_ingest_max", 65536)  # queued events per shard; drop beyond

# --- cluster health plane (metrics history + health monitor) ---
# two-tier metrics time-series ring kept by the GCS over the snapshots it
# already receives: a raw tier sampled every metrics_history_interval_s and
# a rollup tier aggregating raw points every metrics_history_rollup_s
_d("metrics_history_interval_s", 5.0)
_d("metrics_history_raw_points", 360)     # ~30 min of raw tier
_d("metrics_history_rollup_s", 60.0)
_d("metrics_history_rollup_points", 1440)  # ~24 h of rollup tier
# GCS health monitor: scans task events + metrics for stuck tasks,
# straggler nodes, and dead-zygote/pool starvation
_d("health_scan_interval_s", 5.0)
_d("health_stuck_min_s", 30.0)       # floor: RUNNING younger is never stuck
_d("health_stuck_p99_factor", 5.0)   # stuck if age > factor * per-fn p99
_d("health_stuck_fallback_s", 600.0)  # no completed samples for the fn yet
_d("health_straggler_factor", 3.0)   # outlier if > factor * cluster median
_d("health_warn_interval_s", 60.0)   # rate limit for health warning logs

# --- goodput ledger (per-job wall-clock attribution) ---
_d("goodput_enabled", True)
# findings ignore jobs with less than this much ledger wall time (startup
# transients would otherwise trip the fraction thresholds)
_d("goodput_min_wall_s", 5.0)
_d("goodput_recompile_storm_n", 3)     # recompiles within the window ->
_d("goodput_recompile_window_s", 300.0)  # recompile_storm finding
_d("goodput_input_bound_frac", 0.25)   # input_stall/wall over this -> finding
_d("goodput_ckpt_budget_s", 5.0)       # mean ckpt pause per save budget
# goodput_fraction this far (absolute) below the job's trailing-window
# mean -> goodput_regression finding; needs this many history points
_d("goodput_regression_drop", 0.1)
_d("goodput_regression_min_points", 6)

# --- checkpoint storage tier (ckpt/tier) ---
_d("ckpt_io_threads", 8)  # per-host parallel chunk transfer workers
# per-host in-flight payload byte cap for cross-tier chunk transfers
_d("ckpt_io_inflight_bytes", 256 * 1024**2)
# ranged reads separated by at most this many bytes coalesce into one GET
_d("ckpt_io_coalesce_gap", 64 * 1024)
_d("ckpt_mirror_enabled", True)  # TieredStore commits enqueue a mirror
_d("ckpt_multipart_bytes", 8 * 1024**2)  # bucket uploads split above this
# GCS-side retention sweeper cadence over opted-in stores (0 disables)
_d("ckpt_sweep_interval_s", 30.0)
# chunks younger than this are never reaped on any tier (in-flight saves
# and mirrors write chunks before the manifest that names them)
_d("ckpt_sweep_grace_s", 300.0)
# when set, train-run checkpoint stores become TieredStores mirroring to
# a bucket rooted here (one prefix per run); "" keeps them local-only
_d("ckpt_tier_root", "")

# --- train / libs ---
_d("train_health_check_period_s", 1.0)
_d("serve_proxy_port", 8000)
# consecutive failed health checks before a slow-but-alive replica is
# replaced (first-request XLA compiles can starve health replies)
_d("serve_health_strikes", 30)

# --- logging / session ---
_d("session_root", "/tmp/ray_tpu_sessions")
_d("log_to_driver", True)

"""Pluggable external storage for object spilling.

Reference: python/ray/_private/external_storage.py — spilled objects go to
a configured backend (local filesystem, NFS mount, S3, or a user plugin),
identified per object by an opaque URI the store hands back on restore or
delete. Config (RAY_TPU_OBJECT_SPILL_STORAGE):

- ``""`` / ``"filesystem"``  — local directory (object_spill_dir)
- ``"module.path:ClassName"`` — user plugin implementing ExternalStorage,
  constructed with the spill directory as its single argument
- ``"s3://bucket/prefix"``   — S3 via boto3 (gated: raises at setup if
  boto3 is absent — nothing in the base image needs it)
"""

from __future__ import annotations

import os
from typing import Union


class ExternalStorage:
    """Spill backend interface (reference: external_storage.py
    ExternalStorage.spill_objects/restore_spilled_objects)."""

    def spill(self, key: str, data: Union[bytes, memoryview]) -> str:
        """Persist ``data`` under ``key``; returns the object's URI."""
        raise NotImplementedError

    def restore(self, uri: str) -> bytes:
        raise NotImplementedError

    def restore_range(self, uri: str, offset: int, length: int) -> bytes:
        """Ranged read for chunked transfers of spilled objects; backends
        with native range support (fs seek, S3 Range header) override."""
        return self.restore(uri)[offset: offset + length]

    def delete(self, uri: str) -> None:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """Default backend: one file per object in a local (or NFS) directory."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def spill(self, key: str, data: Union[bytes, memoryview]) -> str:
        path = os.path.join(self.directory, key)
        with open(path, "wb") as f:
            f.write(data)
        return path

    def restore(self, uri: str) -> bytes:
        with open(uri, "rb") as f:
            return f.read()

    def restore_range(self, uri: str, offset: int, length: int) -> bytes:
        with open(uri, "rb") as f:
            f.seek(offset)
            return f.read(length)

    def delete(self, uri: str) -> None:
        try:
            os.unlink(uri)
        except FileNotFoundError:
            pass


class S3Storage(ExternalStorage):
    """S3 backend (boto3-gated; key layout <prefix>/<object-key>).

    Capacity tier: transfers run synchronously on the store's event loop
    (same execution model as the filesystem backend, but network-bound) —
    suited to overflow capacity and archival, not hot-path spill churn.
    Async offload of external transfers is tracked as future work."""

    def __init__(self, bucket: str, prefix: str):
        try:
            import boto3
        except ImportError as e:  # pragma: no cover - boto3 not in image
            raise RuntimeError(
                "object_spill_storage=s3://... needs boto3, which is not "
                "installed") from e
        self._s3 = boto3.client("s3")
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    def spill(self, key: str, data: Union[bytes, memoryview]) -> str:
        full = f"{self.prefix}/{key}" if self.prefix else key
        self._s3.put_object(Bucket=self.bucket, Key=full, Body=bytes(data))
        return f"s3://{self.bucket}/{full}"

    def restore(self, uri: str) -> bytes:
        key = uri[len(f"s3://{self.bucket}/"):]
        return self._s3.get_object(Bucket=self.bucket,
                                   Key=key)["Body"].read()

    def restore_range(self, uri: str, offset: int, length: int) -> bytes:
        key = uri[len(f"s3://{self.bucket}/"):]
        rng = f"bytes={offset}-{offset + length - 1}"
        return self._s3.get_object(Bucket=self.bucket, Key=key,
                                   Range=rng)["Body"].read()

    def delete(self, uri: str) -> None:
        key = uri[len(f"s3://{self.bucket}/"):]
        self._s3.delete_object(Bucket=self.bucket, Key=key)


def setup_external_storage(spec: str, default_dir: str) -> ExternalStorage:
    """Resolve the configured spill backend (see module docstring)."""
    spec = (spec or "").strip()
    if spec in ("", "filesystem"):
        return FileSystemStorage(default_dir)
    if spec.startswith("s3://"):
        rest = spec[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        if not bucket:
            raise ValueError(f"bad s3 spill spec {spec!r}")
        return S3Storage(bucket, prefix)
    if ":" in spec:
        import importlib

        mod_name, _, cls_name = spec.partition(":")
        cls = getattr(importlib.import_module(mod_name), cls_name)
        storage = cls(default_dir)
        if not isinstance(storage, ExternalStorage):
            raise TypeError(
                f"{spec!r} must construct an ExternalStorage, got "
                f"{type(storage).__name__}")
        return storage
    raise ValueError(f"unrecognized object_spill_storage spec {spec!r}")

"""Shared runtime datatypes: task/actor specs, resources, node info.

Equivalent of the reference's ``src/ray/common`` task/lease specifications
(``common/task/``, ``common/lease/``) and scheduling datatypes
(``common/scheduling/cluster_resource_data.h``, ``label_selector.h``) —
re-based on a TPU-first resource model: ``TPU`` chips are a first-class
resource and every node carries labels (slice name, pod type, worker id,
ICI topology) that the scheduler can select on.
"""

from __future__ import annotations

import os

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID

# Well-known node label keys (reference: python/ray/_private/accelerators/tpu.py,
# ray._raylet label constants).
LABEL_NODE_ID = "ray_tpu.io/node-id"
LABEL_TPU_SLICE = "ray_tpu.io/tpu-slice-name"
LABEL_TPU_POD_TYPE = "ray_tpu.io/tpu-pod-type"
LABEL_TPU_WORKER_ID = "ray_tpu.io/tpu-worker-id"
LABEL_TPU_TOPOLOGY = "ray_tpu.io/tpu-topology"
LABEL_MARKET_TYPE = "ray_tpu.io/market-type"


def resources_ge(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in need.items())


def resources_sub(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


def resources_add(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) + v


def label_match(labels: Dict[str, str], selector: Dict[str, str]) -> bool:
    """Equality / negation ("!value") / "in" ("a|b") selector semantics.

    Reference: src/ray/common/scheduling/label_selector.h.
    """
    for key, want in selector.items():
        have = labels.get(key)
        if want.startswith("!"):
            if have == want[1:]:
                return False
        elif "|" in want:
            if have not in want.split("|"):
                return False
        elif have != want:
            return False
    return True


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str  # raylet rpc address host:port
    object_store_address: str
    total_resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    is_head: bool = False
    start_time: float = field(default_factory=time.time)

    def to_dict(self):
        return {
            "node_id": self.node_id.hex(),
            "address": self.address,
            "object_store_address": self.object_store_address,
            "total_resources": dict(self.total_resources),
            "labels": dict(self.labels),
            "alive": self.alive,
            "is_head": self.is_head,
        }


@dataclass
class TaskOptions:
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: Dict[str, float] = field(default_factory=dict)
    memory: Optional[float] = None
    num_returns: int = 1
    max_retries: int = -1  # -1 -> config default
    retry_exceptions: bool = False
    name: str = ""
    label_selector: Dict[str, str] = field(default_factory=dict)
    scheduling_strategy: Any = None  # see util/scheduling_strategies.py
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    runtime_env: Optional[Dict[str, Any]] = None

    def required_resources(self) -> Dict[str, float]:
        req = dict(self.resources)
        if self.num_cpus:
            req["CPU"] = req.get("CPU", 0.0) + self.num_cpus
        if self.num_tpus:
            req["TPU"] = req.get("TPU", 0.0) + self.num_tpus
        if self.memory:
            req["memory"] = req.get("memory", 0.0) + self.memory
        return req


@dataclass
class ActorOptions(TaskOptions):
    num_cpus: float = 1.0
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    max_pending_calls: int = -1
    lifetime: str = "ref_counted"  # "ref_counted" | "detached"
    namespace: str = "default"
    get_if_exists: bool = False


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    function_key: str  # GCS KV key holding the pickled function / class
    args_blob: bytes  # serialized (args, kwargs) with ObjectRefs preserved
    num_returns: int
    options: TaskOptions
    owner_address: str = ""
    # actor-task fields
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    seqno: int = -1
    # device-object transport tag (reference: @ray.method(tensor_transport))
    tensor_transport: str = ""
    # actor-creation fields
    is_actor_creation: bool = False
    actor_options: Optional[ActorOptions] = None
    attempt: int = 0
    # trace-context propagation (reference: TaskSpec's serialized OTel
    # context in tracing_helper.py): the submitting side stamps the caller's
    # active span so execution-side spans and nested submissions form one
    # cross-process trace tree. Empty when tracing is disabled.
    trace_id: str = ""
    parent_span_id: str = ""

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)]


@dataclass
class Bundle:
    resources: Dict[str, float]
    label_selector: Dict[str, str] = field(default_factory=dict)


@dataclass
class PlacementGroupSpec:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    strategy: str = "PACK"  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    name: str = ""
    lifetime: str = "ref_counted"
    creator_job: Optional[JobID] = None


@dataclass
class ActorState:  # raylint: disable=WIRE001 GCS-local bookkeeping record; never crosses RPC
    PENDING = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


@dataclass
class WorkerLease:
    lease_id: str
    worker_address: str
    worker_pid: int
    node_id: NodeID
    resources: Dict[str, float]


def die_with_parent():
    """Bind this process's lifetime to its parent (PR_SET_PDEATHSIG).

    Called by the CHILD at startup instead of a Popen preexec_fn: a
    preexec_fn forces subprocess to fork() — which intermittently
    crashes/deadlocks when the parent is multithreaded (JAX drivers are).
    Without preexec_fn, subprocess uses posix_spawn. The exec-to-call
    window can orphan a child if the parent dies in it; the session
    sweep reclaims those."""
    try:
        import ctypes
        import signal

        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGKILL)
        # close the exec->arm window: the spawner records its pid in the
        # child env; a mismatch means the parent died (child reparented)
        # before we armed. Comparing against a literal init pid would
        # misfire when the supervisor legitimately IS pid 1 (containers).
        expected = os.environ.get("RAY_TPU_PARENT_PID")
        if expected and os.getppid() != int(expected):
            os._exit(0)
    except Exception:  # raylint: disable=EXC001 best-effort orphan check in child bootstrap; must never block worker start
        pass

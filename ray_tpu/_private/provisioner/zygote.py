"""The zygote process: pre-imports the heavy stack, forks workers on demand.

Runs as ``python -m ray_tpu._private.provisioner.zygote --control-fd N``
with one end of a socketpair inherited from the raylet. The protocol is
length-prefixed JSON frames (4-byte big-endian length):

  -> {"op": "ping", "seq": k}                 <- {"op": "pong", "seq": k, ...}
  -> {"op": "fork", "seq": k, "args": {...}}  <- {"op": "forked", "seq": k,
                                                  "pid": p}
  (async, no seq)                             <- {"op": "exit", "pid": p,
                                                  "code": c}

Fork safety: the zygote is strictly single-threaded and never runs an event
loop — every import below must keep it that way (JAX starts worker threads,
so it is only pre-imported behind ``zygote_preimport_jax``). The fork child
closes the control fd, resets inherited signal/prctl state, and enters the
shared ``worker_main.run_worker`` bootstrap; the parent reaps children with
``waitpid(WNOHANG)`` and streams exit events back to the raylet.
"""

from __future__ import annotations

import os
import select
import signal
import traceback

from ray_tpu._private.provisioner.framing import FrameReader, send_frame


def preimport(preimport_jax: bool = False) -> list:
    """Pay the import cost ONCE, before any fork: everything a worker needs
    at start-up (serialization, rpc, the worker runtime) plus the usual
    numeric stack. Returns the module names made resident (for the pong)."""
    mods = [
        "cloudpickle",
        "numpy",
        "ray_tpu",
        "ray_tpu._private.core_worker",
        "ray_tpu._private.object_store",
        "ray_tpu._private.rpc",
        "ray_tpu._private.runtime_env",
        "ray_tpu._private.serialization",
        "ray_tpu._private.task_events",
        "ray_tpu._private.wire",
        "ray_tpu._private.worker_main",
    ]
    if preimport_jax:
        mods.append("jax")
    loaded = []
    for mod in mods:
        try:
            __import__(mod)
            loaded.append(mod)
        except Exception:  # keep serving: the worker will fail visibly later
            traceback.print_exc()
    return loaded


def _clear_pdeathsig() -> None:
    """The fork child inherits the zygote's PR_SET_PDEATHSIG (armed against
    the raylet). Left in place it would SIGKILL every worker the moment the
    zygote exits — clear it; orphan detection is the ppid poll in
    run_worker instead."""
    try:
        import ctypes

        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(1, 0)  # PR_SET_PDEATHSIG, no signal
    except Exception:  # raylint: disable=EXC001 best-effort prctl reset in fork child
        pass


def _child_main(control_fd: int, args: dict, zygote_pid: int) -> "None":
    """Post-fork worker bootstrap. Never returns.

    ``zygote_pid`` is the parent's pid captured BEFORE the fork: calling
    ``os.getppid()`` here instead would race a zygote that dies in the fork
    window (the child would record init's pid and never detect orphaning).
    """
    code = 0
    try:
        os.close(control_fd)
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        _clear_pdeathsig()
        # the PRNG state is part of the zygote image: without a reseed every
        # forked worker would draw the same "random" stream (numpy's global
        # RandomState is preimported, so it needs its own reseed)
        import random
        import sys

        random.seed()
        if "numpy" in sys.modules:
            sys.modules["numpy"].random.seed()
        from ray_tpu._private.worker_main import (
            reset_observability_after_fork, run_worker)

        # the zygote image holds live span/task-event buffers and a metric
        # registry; the child must not re-emit them as its own
        reset_observability_after_fork()
        run_worker(
            args["raylet_address"], args["gcs_address"], args["node_id"],
            log_dir=args.get("log_dir", ""),
            runtime_env=args.get("runtime_env"),
            orphan_ppid=zygote_pid,
        )
    except BaseException:
        traceback.print_exc()
        code = 1
    finally:
        # skip atexit/gc of state shared with the zygote image
        os._exit(code)


def serve(control_fd: int, preimport_jax: bool = False) -> None:
    loaded = preimport(preimport_jax)
    reader = FrameReader()
    my_pid = os.getpid()
    while True:
        try:
            ready, _, _ = select.select([control_fd], [], [], 0.2)
        except InterruptedError:  # raylint: disable=EXC001 EINTR on select: retry
            continue
        # reap forked children and stream exits to the raylet
        while True:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:  # raylint: disable=EXC001 no children to reap
                break
            if pid == 0:
                break
            send_frame(control_fd, {
                "op": "exit", "pid": pid,
                "code": os.waitstatus_to_exitcode(status)})
        if not ready:
            continue
        try:
            data = os.read(control_fd, 1 << 16)
        except OSError:  # raylint: disable=EXC001 control fd gone: raylet died, exit quietly
            return
        if not data:
            return  # raylet closed its end: we're done
        for msg in reader.feed(data):
            op = msg.get("op")
            if op == "ping":
                send_frame(control_fd, {"op": "pong", "seq": msg.get("seq"),
                                        "pid": os.getpid(),
                                        "preimported": loaded})
            elif op == "fork":
                try:
                    pid = os.fork()
                except OSError as e:
                    # EAGAIN under the very burst load we exist to serve
                    # (or a pids cgroup limit): stay up, report the
                    # failure for THIS request only
                    send_frame(control_fd, {
                        "op": "forked", "seq": msg.get("seq"),
                        "error": f"fork failed: {e}"})
                    continue
                if pid == 0:
                    _child_main(control_fd, msg["args"], my_pid)  # no return
                send_frame(control_fd, {"op": "forked", "seq": msg.get("seq"),
                                        "pid": pid})
            elif op == "crash":  # fault injection for tests
                os._exit(42)


def main():
    from ray_tpu._private.common import die_with_parent

    die_with_parent()

    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--control-fd", type=int, required=True)
    parser.add_argument("--preimport-jax", action="store_true")
    args = parser.parse_args()
    # stdout/stderr are the raylet's worker log; keep our own chatter out of
    # the frame channel (which is a dedicated fd)
    try:
        serve(args.control_fd, preimport_jax=args.preimport_jax)
    except KeyboardInterrupt:  # raylint: disable=EXC001 clean ^C shutdown path
        pass
    # zygote exits quietly when the raylet goes away; forked children notice
    # via their ppid poll


if __name__ == "__main__":
    main()

"""Raylet-side half of the provisioning plane.

``WorkerProvisioner`` owns the zygote subprocess + its control channel and
routes worker spawns: zygote fork for default-interpreter workers (fast —
imports are resident in the zygote image), cold ``Popen`` for pip/uv envs,
zygote death, or fork-less platforms. It also keeps the warm pool topped up
(``worker_pool_warm_target``) so lease grants are pure adoption, and owns
the pool counters/histograms surfaced through ``/metrics`` and
``/api/workers``.

Reference: ``worker_pool.h:276`` (PopWorker/PrestartWorkers and the
registered-idle pool) — the zygote itself has no reference analog; it
replaces the per-spawn interpreter+import cost the reference pays in
``StartWorkerProcess``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, Optional

from ray_tpu._private.async_util import spawn
from ray_tpu._private.config import RAY_CONFIG
from ray_tpu._private.provisioner.framing import FrameReader, encode_frame

logger = logging.getLogger("ray_tpu.provisioner")

_pool_instruments = None


def _obs():
    """Lazy pool instruments (ride the process's auto-published registry)."""
    global _pool_instruments
    if _pool_instruments is None:
        from ray_tpu.util.metrics import Counter, Histogram

        _pool_instruments = {
            "hits": Counter("ray_tpu_worker_pool_hits",
                            "lease grants served by adopting a warm worker"),
            "misses": Counter("ray_tpu_worker_pool_misses",
                              "lease grants that had to spawn a worker"),
            "forks": Counter("ray_tpu_worker_pool_forks",
                             "workers forked from the zygote"),
            "cold": Counter("ray_tpu_worker_pool_cold_spawns",
                            "workers cold-spawned via subprocess.Popen"),
            "zygote_restarts": Counter(
                "ray_tpu_worker_pool_zygote_restarts",
                "zygote crashes followed by a respawn"),
            "adoption": Histogram(
                "ray_tpu_worker_adoption_seconds",
                "lease-grant worker acquisition latency (warm pop or spawn)",
                boundaries=[0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0, 30.0]),
            "grant_batch": Histogram(
                "ray_tpu_lease_grant_batch_size",
                "grants returned per RequestWorkerLease reply",
                boundaries=[1, 2, 4, 8, 16, 32]),
        }
    return _pool_instruments


def fork_supported() -> bool:
    return hasattr(os, "fork") and sys.platform.startswith("linux")


class ForkedProc:
    """Popen-compatible view of a zygote-forked worker: exit codes come
    from the zygote's reap stream; liveness probing covers a dead zygote."""

    def __init__(self, pid: int, provisioner: "WorkerProvisioner"):
        self.pid = pid
        self._prov = provisioner
        # which zygote forked us: a worker of a crashed generation has NO
        # reaper (it reparented to init), even if a respawned zygote is
        # alive — its exit event will never arrive
        self._gen = provisioner.generation
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        code = self._prov.reaped_exit(self.pid)
        if code is None and (self._gen != self._prov.generation
                             or not self._prov.zygote_alive):
            # no reaper for this worker: probe the pid directly (same uid)
            try:
                os.kill(self.pid, 0)
            except ProcessLookupError:
                code = -1
            except PermissionError:  # raylint: disable=EXC001 pid exists but other uid: not ours to call dead
                pass
        if code is not None:
            self.returncode = code
        return self.returncode

    def _signal(self, sig: int):
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            if self.returncode is None:
                self.returncode = -1

    def kill(self):
        self._signal(signal.SIGKILL)

    def terminate(self):
        self._signal(signal.SIGTERM)


class WorkerProvisioner:
    """Zygote lifecycle + fork RPCs + warm-pool replenishment for one
    raylet. All coroutines run on the raylet's event loop."""

    def __init__(self, raylet):
        self.raylet = raylet
        self.enabled = bool(RAY_CONFIG.worker_zygote_enabled) \
            and fork_supported()
        self._proc: Optional[subprocess.Popen] = None
        self._sock: Optional[socket.socket] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._exits: Dict[int, int] = {}
        self._seq = 0
        self._ready = False
        self._respawning = False
        self._closed = False
        self.generation = 0  # bumps per zygote (re)spawn; see ForkedProc
        # readiness-ping failures since the last successful boot: once a
        # boot has failed, fork_worker stops PARKING on in-flight boots
        # (cold spawn immediately) so a zygote that can never become ready
        # cannot wedge the node's whole spawn path
        self._boot_failures = 0
        # counters mirrored to GetNodeStats + the "workers" KV namespace
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "forks": 0, "cold_spawns": 0,
            "zygote_restarts": 0, "fork_failures": 0,
        }
        # renv-keyed warm pool: the most-recently-leased non-default
        # runtime env (hash, env dict). The replenish loop keeps warm
        # workers forked for it too, so a hot non-default env stops
        # bypassing the pool (every grant was a fork: STRESS_r06 showed
        # 113 misses vs 72 hits on the hot node for exactly this reason).
        self.hot_renv: Optional[tuple] = None

    def note_renv(self, renv_hash: str, renv: Optional[dict]):
        """Record the most-recently-requested runtime env for replenish
        keying. Only zygote-forkable envs qualify (pip envs — including
        uv, which normalize() folds into the "pip" key — run a different
        interpreter and can never come from the pool)."""
        if renv_hash and renv and "pip" not in renv:
            self.hot_renv = (renv_hash, dict(renv))

    # -- zygote lifecycle ----------------------------------------------

    @property
    def zygote_alive(self) -> bool:
        return (self._ready and self._proc is not None
                and self._proc.poll() is None)

    async def start(self):
        if not self.enabled:
            return
        try:
            await self._spawn_zygote()
        except Exception:
            logger.warning("zygote start failed; cold spawns only",
                           exc_info=True)
            self._abort_boot()

    def _abort_boot(self):
        """A zygote that missed its readiness ping must not linger half-up:
        a live-but-never-ready process would make _wait_ready park every
        spawn for the full timeout. Kill it so the state is unambiguous
        (the reader's EOF handler owns any respawn)."""
        self._boot_failures += 1
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._proc.kill()
            except Exception as e:
                logger.debug("boot-abort zygote kill failed: %s", e)

    async def _wait_ready(self, timeout: float) -> bool:
        """Wait for an in-flight zygote BOOT (start() runs in the
        background so the raylet registers immediately). A crashed or
        absent zygote returns False at once — callers cold-spawn rather
        than stalling behind the respawn backoff."""
        deadline = time.monotonic() + timeout
        while not self._closed and time.monotonic() < deadline:
            if self.zygote_alive:
                return True
            if self._boot_failures:
                # a boot already failed once: don't park lease-driven
                # spawns behind retry attempts — cold spawn now, adopt the
                # zygote whenever a retry finally succeeds
                return False
            if self._proc is None or self._proc.poll() is not None:
                return False
            await asyncio.sleep(0.05)
        return self.zygote_alive

    async def close(self):
        self._closed = True
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:  # raylint: disable=EXC001 already-closed control socket at shutdown
                pass
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._proc.kill()
            except Exception as e:
                logger.debug("zygote kill at close failed: %s", e)

    async def _spawn_zygote(self):
        self.generation += 1
        parent_sock, child_sock = socket.socketpair()
        cmd = [sys.executable, "-m", "ray_tpu._private.provisioner.zygote",
               "--control-fd", str(child_sock.fileno())]
        if RAY_CONFIG.zygote_preimport_jax:
            cmd.append("--preimport-jax")
        self._proc = subprocess.Popen(
            cmd, env=self.raylet._spawn_env,
            pass_fds=[child_sock.fileno()],
            stdout=self.raylet._log_file("worker_stdout"),
            stderr=subprocess.STDOUT)
        child_sock.close()
        parent_sock.setblocking(False)
        self._sock = parent_sock
        self._reader_task = spawn(self._reader_loop(parent_sock),
                                  what="zygote control reader")
        # wait for the preimport to finish: first fork must be warm
        reply = await self._request({"op": "ping"},
                                    timeout=RAY_CONFIG.worker_start_timeout_s)
        self._ready = True
        self._boot_failures = 0
        logger.info("zygote pid=%d ready (%d modules resident)",
                    self._proc.pid, len(reply.get("preimported", ())))

    async def _reader_loop(self, sock: socket.socket):
        loop = asyncio.get_event_loop()
        reader = FrameReader()
        try:
            while True:
                try:
                    data = await loop.sock_recv(sock, 1 << 16)
                except (OSError, ValueError):
                    data = b""
                if not data:
                    break
                for msg in reader.feed(data):
                    op = msg.get("op")
                    if op == "exit":
                        self._exits[int(msg["pid"])] = int(msg["code"])
                        if len(self._exits) > 4096:
                            self._exits.pop(next(iter(self._exits)))
                    elif op in ("pong", "forked"):
                        if op == "forked" and msg.get("pid") is not None:
                            # pid-reuse defense, done HERE and not in
                            # fork_worker: the zygote always sends 'forked'
                            # before that child's 'exit', and frames are
                            # processed in order — so any exit record
                            # present now is from a previous incarnation
                            # of this pid, while popping later (after the
                            # awaiting coroutine resumes) could erase a
                            # genuine crash-at-bootstrap exit
                            self._exits.pop(int(msg["pid"]), None)
                        fut = self._pending.pop(msg.get("seq"), None)
                        if fut is not None and not fut.done():
                            fut.set_result(msg)
        finally:
            if sock is self._sock:
                self._on_zygote_death()

    def _on_zygote_death(self):
        self._ready = False
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(RuntimeError("zygote died"))
        self._pending.clear()
        if self._closed or self._respawning:
            return
        self._respawning = True
        spawn(self._respawn(), what="zygote respawn")

    async def _respawn(self):
        """Zygote crashed: back off briefly, then rebuild it. Meanwhile
        spawn_worker falls back to cold Popen."""
        try:
            delay = 0.2
            while not self._closed:
                await asyncio.sleep(delay)
                try:
                    if self._sock is not None:
                        self._sock.close()
                    await self._spawn_zygote()
                    self.stats["zygote_restarts"] += 1
                    _obs()["zygote_restarts"].inc()
                    logger.warning("zygote respawned after crash")
                    return
                except Exception as e:
                    logger.warning("zygote respawn failed (retrying): %s", e)
                    self._abort_boot()
                    delay = min(delay * 2, 5.0)
        finally:
            self._respawning = False

    async def _request(self, msg: dict, timeout: float) -> dict:
        assert self._sock is not None
        self._seq += 1
        seq = self._seq
        msg = dict(msg, seq=seq)
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        self._pending[seq] = fut
        try:
            await loop.sock_sendall(self._sock, encode_frame(msg))
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(seq, None)

    # -- spawn routing --------------------------------------------------

    def reaped_exit(self, pid: int) -> Optional[int]:
        return self._exits.get(pid)

    async def fork_worker(self, renv: Optional[dict]) -> Optional[int]:
        """Ask the zygote for a worker; returns the pid, or None when the
        zygote path is unavailable (caller cold-spawns)."""
        if not self.enabled:
            return None
        # wait at most HALF the start timeout for an in-flight zygote boot:
        # the cold-spawn fallback still has to fit its own registration
        # wait inside the owner's RequestWorkerLease RPC budget
        # (worker_start_timeout_s + 30 on the caller side)
        if not self.zygote_alive and not await self._wait_ready(
                RAY_CONFIG.worker_start_timeout_s / 2):
            return None
        raylet = self.raylet
        args = {
            "raylet_address": raylet.server.address,
            "gcs_address": raylet.gcs_address,
            "node_id": raylet.node_id.hex(),
            "log_dir": raylet.log_dir,
            "runtime_env": renv,
        }
        try:
            reply = await self._request(
                {"op": "fork", "args": args},
                timeout=RAY_CONFIG.zygote_fork_timeout_s)
            if reply.get("error"):
                # zygote stayed up but THIS fork failed (EAGAIN / pid
                # limit): cold-spawn this one worker
                self.stats["fork_failures"] += 1
                logger.warning("zygote fork refused: %s", reply["error"])
                return None
            pid = int(reply["pid"])
            self.stats["forks"] += 1
            _obs()["forks"].inc()
            return pid
        except (RuntimeError, asyncio.TimeoutError, OSError) as e:
            self.stats["fork_failures"] += 1
            logger.warning("zygote fork failed (falling back to cold "
                           "spawn): %s", e)
            return None

    async def crash_zygote_for_test(self):
        """Fault injection: make the zygote exit abruptly."""
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()

    # -- warm pool replenishment ----------------------------------------

    async def replenish_loop(self):
        """Keep ``worker_pool_warm_target`` default-env workers — PLUS
        ``worker_pool_warm_target_renv`` workers keyed to the most-recently
        -leased non-default runtime env (``note_renv``) — forked AND
        registered so lease grants adopt instead of spawning. Zygote-only:
        when the zygote is down, topping up via cold Popen would burn the
        very CPU the pending leases need."""
        target = max(0, int(RAY_CONFIG.worker_pool_warm_target))
        renv_target = max(0, int(RAY_CONFIG.worker_pool_warm_target_renv))
        if (target == 0 and renv_target == 0) or not self.enabled:
            return
        raylet = self.raylet
        while True:
            await asyncio.sleep(0.25)
            try:
                # evict warm workers keyed to a renv that is no longer hot:
                # without this, cycling through unique runtime envs leaves
                # up to renv_target idle workers behind per env until
                # max_workers_per_node starves both replenish and top-up.
                # Runs BEFORE the zygote/capacity gate below — a node at
                # max_workers_per_node is exactly the starved state this
                # must dig out of, and the kill is a plain SIGKILL that
                # needs no live zygote. Only never-leased pool forks
                # qualify (job_hex None); removal from idle_workers is
                # synchronous so a concurrent grant can't adopt a worker
                # we are about to kill — the death monitor reaps the rest
                # of the bookkeeping.
                hot_hash = self.hot_renv[0] if self.hot_renv else ""
                for w in list(raylet.idle_workers):
                    if w.job_hex is None and w.renv_hash \
                            and w.renv_hash != hot_hash:
                        raylet.idle_workers.remove(w)
                        try:
                            w.proc.kill()
                        except Exception as e:
                            logger.debug("stale-renv evict of pid %d "
                                         "failed: %s", w.pid, e)
                if not self.zygote_alive \
                        or len(raylet.workers) >= RAY_CONFIG.max_workers_per_node:
                    continue
                # one top-up per round, default env first; the hot renv
                # bucket only replenishes once the default pool is full
                renv, renv_hash = None, ""
                warm = sum(1 for w in raylet.idle_workers
                           if w.job_hex is None and not w.renv_hash)
                if warm >= target:
                    if self.hot_renv is None or renv_target == 0:
                        continue
                    renv_hash, renv = self.hot_renv
                    warm_renv = sum(1 for w in raylet.idle_workers
                                    if w.job_hex is None
                                    and w.renv_hash == renv_hash)
                    if warm_renv >= renv_target:
                        continue
                w = None
                async with raylet._spawn_sem:
                    # fork directly, NEVER through the cold-Popen fallback:
                    # a refused fork (EAGAIN, zygote mid-crash) just skips
                    # this top-up round
                    pid = await self.fork_worker(renv)
                    if pid is None:
                        continue
                    w = raylet._register_forked(pid, renv_hash)
                    try:
                        await asyncio.wait_for(
                            w.registered, RAY_CONFIG.worker_start_timeout_s)
                    except asyncio.TimeoutError:
                        # kill + untrack: a late registrant would sit in
                        # raylet.workers but never join idle_workers, and
                        # repeating rounds would strand live processes
                        # until max_workers_per_node is consumed
                        logger.warning("warm-pool replenish: registration "
                                       "timed out; reaping pid %d", w.pid)
                        try:
                            w.proc.kill()
                        except Exception as e:
                            logger.debug("replenish reap of pid %d "
                                         "failed: %s", w.pid, e)
                        raylet.workers.pop(w.pid, None)
                        continue
                w.job_hex = None
                if w.pid in raylet.workers and w not in raylet.idle_workers:
                    raylet.idle_workers.append(w)
            except Exception:
                logger.exception("warm-pool replenish iteration failed")

    # -- introspection --------------------------------------------------

    def snapshot(self) -> dict:
        raylet = self.raylet
        hot_hash = self.hot_renv[0] if self.hot_renv else ""
        return {
            "enabled": self.enabled,
            "zygote_alive": self.zygote_alive,
            "zygote_pid": self._proc.pid if self._proc else None,
            "warm_target": int(RAY_CONFIG.worker_pool_warm_target),
            "idle_workers": len(raylet.idle_workers),
            "warm_default_env": sum(
                1 for w in raylet.idle_workers
                if w.job_hex is None and not w.renv_hash),
            "hot_renv_hash": hot_hash,
            "warm_hot_renv": sum(
                1 for w in raylet.idle_workers
                if w.job_hex is None and hot_hash
                and w.renv_hash == hot_hash),
            "total_workers": len(raylet.workers),
            **self.stats,
        }

"""Length-prefixed JSON framing shared by the zygote and its raylet-side
control channel (kept dependency-free: the zygote imports it before the
heavy preimports, and running ``python -m ...provisioner.zygote`` must not
re-import the module executing as __main__)."""

from __future__ import annotations

import json
import os
import struct

_LEN = struct.Struct(">I")


def encode_frame(msg: dict) -> bytes:
    blob = json.dumps(msg).encode()
    return _LEN.pack(len(blob)) + blob


def send_frame(fd: int, msg: dict) -> None:
    data = encode_frame(msg)
    while data:
        n = os.write(fd, data)
        data = data[n:]


class FrameReader:
    """Incremental length-prefixed JSON frame decoder over a raw fd buffer."""

    def __init__(self):
        self.buf = b""

    def feed(self, data: bytes):
        self.buf += data
        while len(self.buf) >= _LEN.size:
            (n,) = _LEN.unpack(self.buf[:_LEN.size])
            if len(self.buf) < _LEN.size + n:
                return
            blob = self.buf[_LEN.size:_LEN.size + n]
            self.buf = self.buf[_LEN.size + n:]
            yield json.loads(blob)

"""Worker provisioning plane: zygote prefork pool + warm-worker adoption.

Reference: ``src/ray/raylet/worker_pool.h`` (prestart + adoption semantics
behind ``RequestWorkerLease``) and Android's zygote process model. A
per-raylet zygote boots once, pre-imports the heavy stack, then forks ready
workers on demand over a control pipe; the raylet adopts a warm registered
worker on lease grant instead of paying a cold ``Popen`` interpreter+import
start-up. Cold spawn remains the fallback for pip/uv runtime envs (which
need a different interpreter), zygote death, and platforms without fork.
"""

from ray_tpu._private.provisioner.pool import (  # noqa: F401
    ForkedProc,
    WorkerProvisioner,
    fork_supported,
)

"""Runtime environments: per-task/actor env_vars, working_dir, py_modules.

Reference: python/ray/_private/runtime_env/ — plugins install envs on the
node before a worker runs the task (working_dir zips ship via GCS KV,
uri_cache.py dedupes by content hash). TPU-first simplifications: no
conda/pip installation (this image forbids installs; those keys raise), and
the "agent" is folded into the worker pool — the raylet spawns workers with
the runtime-env descriptor and the worker applies it before registering.

Flow:
- driver: ``prepare(core, renv)`` normalizes, zips local dirs, uploads each
  package once to GCS KV (``renv_pkg:<sha1>``), and rewrites the descriptor
  to reference the KV keys;
- lease requests carry the descriptor; the worker pool keys idle workers by
  (job, env-hash) so a worker only ever runs one runtime env;
- worker: ``apply(renv, kv_get)`` sets env vars, downloads + extracts
  packages to a node-local cache dir, prepends them to ``sys.path`` and
  chdirs into the working_dir.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import zipfile
from typing import Any, Callable, Dict, Optional

_PKG_NS = "renv"
_CACHE_ROOT = "/tmp/ray_tpu_runtime_envs"
_UNSUPPORTED = ("pip", "conda", "uv", "container", "image_uri", "java_jars")


def normalize(renv: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not renv:
        return None
    out: Dict[str, Any] = {}
    for k, v in renv.items():
        if k in _UNSUPPORTED:
            raise ValueError(
                f"runtime_env field {k!r} is not supported in this "
                f"environment (package installation is disabled); use "
                f"env_vars / working_dir / py_modules")
        if k == "env_vars":
            if not all(isinstance(a, str) and isinstance(b, str)
                       for a, b in v.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            out["env_vars"] = dict(v)
        elif k in ("working_dir", "py_modules"):
            out[k] = v
        else:
            raise ValueError(f"unknown runtime_env field {k!r}")
    return out or None


def env_hash(renv: Optional[Dict[str, Any]]) -> str:
    if not renv:
        return ""
    return hashlib.sha1(
        json.dumps(renv, sort_keys=True).encode()).hexdigest()[:16]


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", ".venv")]
            for f in files:
                full = os.path.join(root, f)
                z.write(full, os.path.relpath(full, path))
    return buf.getvalue()


def package_dir(path: str) -> tuple:
    """Zip a local dir for upload; returns (sha, blob, basename)."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise FileNotFoundError(f"runtime_env directory not found: {path}")
    blob = _zip_dir(path)
    sha = hashlib.sha1(blob).hexdigest()[:16]
    return sha, blob, os.path.basename(path) or "pkg"


def _extract(pkg: Dict[str, str], kv_get: Callable[[str], Optional[bytes]]
             ) -> str:
    dest = os.path.join(_CACHE_ROOT, pkg["sha"])
    marker = os.path.join(dest, ".ready")
    if not os.path.exists(marker):
        blob = kv_get(pkg["kv_key"])
        if blob is None:
            raise RuntimeError(
                f"runtime_env package {pkg['kv_key']} missing from GCS KV")
        tmp = dest + f".tmp.{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            z.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:  # another worker won the race
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
        with open(marker, "w") as f:
            f.write("ok")
    return dest


def apply(renv: Optional[Dict[str, Any]],
          kv_get: Callable[[str], Optional[bytes]]) -> None:
    """Worker side: make the env effective for this process."""
    if not renv:
        return
    for k, v in (renv.get("env_vars") or {}).items():
        os.environ[k] = v
    for pkg in renv.get("py_modules") or []:
        path = _extract(pkg, kv_get)
        if path not in sys.path:
            sys.path.insert(0, path)
    wd = renv.get("working_dir")
    if wd:
        path = _extract(wd, kv_get)
        if path not in sys.path:
            sys.path.insert(0, path)
        os.chdir(path)

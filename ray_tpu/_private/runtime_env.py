"""Runtime environments: env_vars, working_dir, py_modules, pip/uv venvs.

Reference: python/ray/_private/runtime_env/ — plugins install envs on the
node before a worker runs the task (working_dir zips ship via GCS KV,
uri_cache.py dedupes by content hash; ``pip.py``/``uv.py`` build per-env
virtualenvs keyed by requirement hash and launch workers inside them).
TPU-first simplifications: no conda/containers, and the "agent" is folded
into the worker pool — the raylet resolves the env (creating the venv on
first use) and spawns workers with the runtime-env descriptor; the worker
applies the rest before registering.

Flow:
- driver: ``prepare(core, renv)`` normalizes, zips local dirs, uploads each
  package once to GCS KV (``renv_pkg:<sha1>``), and rewrites the descriptor
  to reference the KV keys;
- lease requests carry the descriptor; the worker pool keys idle workers by
  (job, env-hash) so a worker only ever runs one runtime env;
- raylet: for ``pip``/``uv`` envs, ``ensure_env_python`` builds (once,
  node-locally, under a file lock) a venv that inherits the base
  interpreter's packages and installs the requirements into it; workers for
  that env run on the venv's interpreter (reference:
  _private/runtime_env/pip.py PipProcessor);
- worker: ``apply(renv, kv_get)`` sets env vars, downloads + extracts
  packages to a node-local cache dir, prepends them to ``sys.path`` and
  chdirs into the working_dir.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import subprocess
import sys
import zipfile
from typing import Any, Callable, Dict, Optional

from ray_tpu.exceptions import RuntimeEnvSetupError

_PKG_NS = "renv"
_CACHE_ROOT = "/tmp/ray_tpu_runtime_envs"
_UNSUPPORTED = ("conda", "container", "image_uri", "java_jars")


def normalize(renv: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not renv:
        return None
    out: Dict[str, Any] = {}
    for k, v in renv.items():
        if k in _UNSUPPORTED:
            raise ValueError(
                f"runtime_env field {k!r} is not supported in this "
                f"environment; use env_vars / working_dir / py_modules / "
                f"pip / uv")
        if k == "env_vars":
            if not all(isinstance(a, str) and isinstance(b, str)
                       for a, b in v.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            out["env_vars"] = dict(v)
        elif k in ("pip", "uv"):
            if "pip" in out:
                raise ValueError("runtime_env may carry pip OR uv, not both")
            if isinstance(v, dict):
                pkgs = list(v.get("packages") or [])
            elif isinstance(v, (list, tuple)):
                pkgs = list(v)
            else:
                raise TypeError(f"{k} must be a list of requirements or a "
                                f"dict with 'packages'")
            if not all(isinstance(p, str) for p in pkgs):
                raise TypeError(f"{k} requirements must be strings")
            out["pip"] = {"packages": sorted(pkgs), "installer": k}
        elif k in ("working_dir", "py_modules"):
            out[k] = v
        else:
            raise ValueError(f"unknown runtime_env field {k!r}")
    return out or None


def env_hash(renv: Optional[Dict[str, Any]]) -> str:
    if not renv:
        return ""
    return hashlib.sha1(
        json.dumps(renv, sort_keys=True).encode()).hexdigest()[:16]


def ensure_env_python(renv: Optional[Dict[str, Any]]) -> Optional[str]:
    """Node side: return the interpreter for this env's venv, building it on
    first use (reference: _private/runtime_env/pip.py PipProcessor +
    uv.py). Blocking — callers run it off the event loop.

    The venv is keyed by the requirement spec, inherits the base
    interpreter's site-packages (so jax/numpy/the framework stay visible),
    and is shared by every worker on the node that asks for the same spec.
    A file lock serializes concurrent builders (two raylets on one host).
    """
    if not renv or "pip" not in renv:
        return None
    spec = renv["pip"]
    key = hashlib.sha1(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]
    root = os.path.join(_CACHE_ROOT, "venvs", key)
    py = os.path.join(root, "bin", "python")
    marker = os.path.join(root, ".ready")
    if os.path.exists(marker):
        return py
    import fcntl

    os.makedirs(os.path.dirname(root), exist_ok=True)
    lock_path = root + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):  # lost the race, env is ready
                return py
            _build_venv(root, py, spec)
            with open(marker, "w") as f:
                f.write(json.dumps(spec))
            return py
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _build_venv(root: str, py: str, spec: Dict[str, Any]) -> None:
    import shutil

    if os.path.exists(root):
        shutil.rmtree(root, ignore_errors=True)  # torn previous attempt
    r = subprocess.run(
        [sys.executable, "-m", "venv", "--system-site-packages",
         "--without-pip", root], capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        raise RuntimeEnvSetupError(f"venv creation failed: {r.stderr[-2000:]}")
    # running from a venv, --system-site-packages points at the BASE
    # interpreter's site-packages, not this venv's: bridge ours in so the
    # baked packages (jax, numpy, pip itself) stay importable
    site_dirs = [p for p in sys.path if p.rstrip(os.sep).endswith("site-packages")]
    if site_dirs:
        vsite = os.path.join(
            root, "lib", f"python{sys.version_info[0]}.{sys.version_info[1]}",
            "site-packages")
        with open(os.path.join(vsite, "_ray_tpu_parent.pth"), "w") as f:
            f.write("\n".join(site_dirs) + "\n")
    pkgs = spec["packages"]
    if not pkgs:
        return
    if spec.get("installer") == "uv" and shutil.which("uv"):
        cmd = ["uv", "pip", "install", "--python", py, *pkgs]
    else:
        cmd = [py, "-m", "pip", "install", "--disable-pip-version-check",
               "--no-input", *pkgs]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=1800)
    if r.returncode != 0:
        raise RuntimeEnvSetupError(
            f"requirement install failed ({' '.join(pkgs[:4])}...):\n"
            f"{r.stdout[-1000:]}\n{r.stderr[-2000:]}")


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", ".venv")]
            for f in files:
                full = os.path.join(root, f)
                z.write(full, os.path.relpath(full, path))
    return buf.getvalue()


def package_dir(path: str) -> tuple:
    """Zip a local dir for upload; returns (sha, blob, basename)."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise FileNotFoundError(f"runtime_env directory not found: {path}")
    blob = _zip_dir(path)
    sha = hashlib.sha1(blob).hexdigest()[:16]
    return sha, blob, os.path.basename(path) or "pkg"


def _extract(pkg: Dict[str, str], kv_get: Callable[[str], Optional[bytes]]
             ) -> str:
    dest = os.path.join(_CACHE_ROOT, pkg["sha"])
    marker = os.path.join(dest, ".ready")
    if not os.path.exists(marker):
        blob = kv_get(pkg["kv_key"])
        if blob is None:
            raise RuntimeError(
                f"runtime_env package {pkg['kv_key']} missing from GCS KV")
        tmp = dest + f".tmp.{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            z.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:  # another worker won the race
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
        with open(marker, "w") as f:
            f.write("ok")
    return dest


def apply(renv: Optional[Dict[str, Any]],
          kv_get: Callable[[str], Optional[bytes]]) -> None:
    """Worker side: make the env effective for this process."""
    if not renv:
        return
    for k, v in (renv.get("env_vars") or {}).items():
        os.environ[k] = v
    for pkg in renv.get("py_modules") or []:
        path = _extract(pkg, kv_get)
        if path not in sys.path:
            sys.path.insert(0, path)
    wd = renv.get("working_dir")
    if wd:
        path = _extract(wd, kv_get)
        if path not in sys.path:
            sys.path.insert(0, path)
        os.chdir(path)

"""Per-node shared-memory object store (plasma equivalent).

Reference: ``src/ray/object_manager/plasma`` — an immutable object store with
create/seal/get/delete over a local protocol, LRU eviction with **spill to
disk** (``local_object_manager.h``), and chunked node-to-node transfer
(``object_manager/pull_manager.cc`` / ``push_manager.cc``).

TPU-first deviations from the reference design:
- segments are plain files under /dev/shm mapped with mmap (no dlmalloc arena
  in the Python tier; the C++ arena store in ``src/object_store`` is used when
  built — see ``ray_tpu/_private/cpp_store.py``), so host processes read
  tensors zero-copy before feeding device transfers;
- buffer offsets are 64-byte aligned so numpy/jax can map them directly.

Blob layout inside a segment (written client-side so the store never copies):
  [u32 magic][u64 inband_len][u32 nbuf][(u64 off, u64 len) * nbuf]
  [inband pickle bytes][64-aligned out-of-band buffers...]
"""

from __future__ import annotations

import asyncio
import mmap
import os
import struct
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import RAY_CONFIG

_MAGIC = 0x52545055  # 'RTPU'
_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class ShmSegment:
    """A named /dev/shm file mapping."""

    def __init__(self, name: str, size: Optional[int] = None, create: bool = False):
        self.name = name
        self.path = f"/dev/shm/{name}"
        if create:
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            except FileExistsError:
                # names are single-writer per session: an existing file is a
                # stale leftover from a dead session — reclaim the name,
                # but only if it is old enough (a twin may be between its
                # create and mmap, invisible in /proc) AND no live process
                # maps it: a split-brain twin collides loudly instead of
                # being silently corrupted
                try:
                    age = time.time() - os.stat(self.path).st_mtime
                except FileNotFoundError:
                    age = 1e9  # a racing reclaimer already removed it
                if age < 10.0 or _shm_mapped_by_live_process(name):
                    raise
                try:
                    os.unlink(self.path)
                except FileNotFoundError:
                    pass  # racing reclaimer won; the create below retries
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            os.ftruncate(fd, size)
        else:
            fd = os.open(self.path, os.O_RDWR)
            size = os.fstat(fd).st_size
        try:
            self.buf = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.size = size

    def close(self):
        try:
            self.buf.close()
        except (BufferError, ValueError):  # raylint: disable=EXC001 exported memoryviews still alive; mapping freed at process exit
            pass

    def unlink(self):
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


def _shm_mapped_by_live_process(name: str) -> bool:
    """True when any live process maps /dev/shm/<name> (scans /proc)."""
    import glob

    needle = "/dev/shm/" + name
    for maps in glob.glob("/proc/[0-9]*/maps"):
        try:
            with open(maps) as f:
                for line in f:
                    if needle in line:
                        return True
        except OSError:  # raylint: disable=EXC001 /proc scan: pids exit mid-walk, other-uid maps unreadable
            continue
    return False


def sweep_stale_shm(prefix: str = "rtpu_", min_age_s: float = 10.0) -> int:
    """Remove /dev/shm segments left behind by dead sessions. A segment is
    stale when no live process maps it (scanned via /proc/*/maps) and it is
    older than ``min_age_s`` (guards the create→mmap window of a concurrent
    session). Run at node start (reference: plasma unlinks its store file on
    startup)."""
    import glob

    live = set()
    for maps in glob.glob("/proc/[0-9]*/maps"):
        try:
            with open(maps) as f:
                for line in f:
                    idx = line.find("/dev/shm/" + prefix)
                    if idx >= 0:
                        live.add(line[idx + 9:].split()[0])
        except OSError:  # raylint: disable=EXC001 /proc scan: pids exit mid-walk, other-uid maps unreadable
            continue
    removed = 0
    now = time.time()
    me = os.getuid()
    for path in glob.glob(f"/dev/shm/{prefix}*"):
        try:
            st = os.stat(path)
            # never touch another user's segments: their /proc/*/maps may
            # be unreadable to us, making liveness undecidable
            if st.st_uid != me or os.path.basename(path) in live or \
                    now - st.st_mtime < min_age_s:
                continue
            os.unlink(path)
            removed += 1
        except OSError:  # raylint: disable=EXC001 concurrent GC: another raylet may unlink the segment first
            pass
    return removed


def plan_layout(inband: bytes, buffers: List[memoryview]) -> Tuple[int, List[int]]:
    header = 4 + 8 + 4 + 16 * len(buffers)
    off = _align(header + len(inband))
    offsets = []
    for b in buffers:
        offsets.append(off)
        off = _align(off + b.nbytes)
    return off, offsets


def write_blob(mem, inband: bytes, buffers: List[memoryview], offsets: List[int]):
    header = struct.pack("<IQI", _MAGIC, len(inband), len(buffers))
    pos = len(header)
    mem[0:pos] = header
    for b, off in zip(buffers, offsets):
        mem[pos : pos + 16] = struct.pack("<QQ", off, b.nbytes)
        pos += 16
    mem[pos : pos + len(inband)] = inband
    for b, off in zip(buffers, offsets):
        flat = b if (b.format == "B" and b.ndim == 1) else b.cast("B")
        mem[off : off + b.nbytes] = flat


def read_blob(mem) -> Tuple[bytes, List[memoryview]]:
    view = memoryview(mem)
    magic, inband_len, nbuf = struct.unpack_from("<IQI", view, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt object blob")
    pos = 16
    offsets = []
    for _ in range(nbuf):
        off, length = struct.unpack_from("<QQ", view, pos)
        offsets.append((off, length))
        pos += 16
    inband = bytes(view[pos : pos + inband_len])
    buffers = [view[off : off + length] for off, length in offsets]
    return inband, buffers


def pack_blob(inband: bytes, buffers: List[memoryview]) -> bytes:
    """Serialize the same layout into a contiguous bytes (for inline/wire)."""
    total, offsets = plan_layout(inband, buffers)
    out = bytearray(total)
    write_blob(out, inband, buffers, offsets)
    return bytes(out)


# ---------------------------------------------------------------------------
# Store server (runs inside the raylet process)
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = (
        "state", "shm", "shm_name", "size", "last_access", "spill_path", "inline",
        "arena_offset", "attempt", "arena_key", "owner",
    )

    def __init__(self):
        self.state = "CREATED"  # CREATED | SEALED | SPILLED
        self.shm: Optional[ShmSegment] = None
        self.shm_name = ""
        self.size = 0
        self.last_access = time.monotonic()
        self.spill_path = ""
        self.inline: Optional[bytes] = None
        self.owner = ""  # owner worker address (owner-resident directory)
        self.arena_offset: Optional[int] = None  # set when backed by the arena
        # execution-epoch fence (reference: plasma's seal-once semantics,
        # obj_lifecycle_mgr.cc — here generalized so a retried task's newer
        # attempt replaces a zombie attempt's copy and stale writers abort)
        self.attempt = 0
        self.arena_key: Optional[bytes] = None


class ObjectStoreServer:
    """Node-local store: create/seal/get with LRU spill-to-disk eviction.

    Allocation backends: the native C++ arena (src/object_store/store.cc,
    first-fit + coalescing over one mmap'd /dev/shm file — the plasma-
    allocator equivalent) when built, else one /dev/shm file per object."""

    def __init__(self, node_hex: str, capacity: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self.node_hex = node_hex
        self.capacity = capacity or RAY_CONFIG.object_store_memory
        self.used = 0
        self.spill_dir = spill_dir or (RAY_CONFIG.object_spill_dir or f"/tmp/ray_tpu_sessions/spill_{node_hex[:8]}")
        from ray_tpu._private.external_storage import setup_external_storage

        # pluggable spill backend (reference: _private/external_storage.py):
        # local fs by default; s3://... or a module:Class plugin via config
        self.storage = setup_external_storage(
            RAY_CONFIG.object_spill_storage, self.spill_dir)
        self.objects: Dict[bytes, _Entry] = {}
        self.waiters: Dict[bytes, List[asyncio.Future]] = {}
        self.num_spilled = 0
        self.num_restored = 0
        self.arena = None
        self.arena_name = f"rtpu_arena_{node_hex[:8]}"
        self._arena_view: Optional[ShmSegment] = None
        backend = RAY_CONFIG.object_store_backend
        if backend in ("auto", "cpp"):
            try:
                from ray_tpu._private.cpp_store import CppArena

                self.arena = CppArena(self.arena_name, self.capacity)
                self._arena_view = ShmSegment(self.arena_name)
            except Exception:
                if backend == "cpp":
                    raise
                self.arena = None

    def _shm_name(self, oid: bytes, attempt: int = 0) -> str:
        # attempt-qualified so a retry's copy never aliases a zombie writer's
        # still-mapped file
        suffix = f"_a{attempt}" if attempt else ""
        return f"rtpu_{self.node_hex[:8]}_{oid.hex()}{suffix}"

    def _arena_key(self, oid: bytes, attempt: int) -> bytes:
        # native arena keys are fixed 16 bytes; attempt-salt the key so a
        # replaced entry's region can sit quarantined under its own key
        # while the newer attempt allocates the same object id
        if attempt == 0:
            return oid
        import hashlib

        return hashlib.blake2b(oid + attempt.to_bytes(4, "big"),
                               digest_size=16).digest()

    def _region(self, e: _Entry):
        """Server-side view of an entry's bytes (arena slice or shm file)."""
        if e.arena_offset is not None:
            view = memoryview(self._arena_view.buf)
            return view[e.arena_offset : e.arena_offset + e.size]
        return memoryview(e.shm.buf)[: e.size]

    def _quarantine_arena(self, key: bytes, size: int):
        """Defer freeing a displaced arena region: its (stale) writer may
        still be streaming bytes into a client-side mapping; immediate reuse
        would corrupt the replacement. Freed after a grace period."""
        def _free():
            if self.arena is not None:
                self.arena.free(key)
                self.used -= size
        try:
            asyncio.get_running_loop().call_later(30.0, _free)
        except RuntimeError:
            _free()

    def _evict_for(self, need: int) -> bool:
        """Spill least-recently-used sealed objects until `need` bytes fit."""
        if need > self.capacity:
            return False
        def fits() -> bool:
            if self.arena is not None:
                return self.arena.largest_free() >= need + 64
            return self.used + need <= self.capacity

        if fits():
            return True
        candidates = sorted(
            (e.last_access, oid)
            for oid, e in self.objects.items()
            if e.state == "SEALED"
            and (e.shm is not None or e.arena_offset is not None)
        )
        for _, oid in candidates:
            self._spill(oid)
            if fits():
                return True
        return fits()

    def _spill(self, oid: bytes):
        e = self.objects[oid]
        e.spill_path = self.storage.spill(oid.hex(), self._region(e))
        e.state = "SPILLED"
        if e.arena_offset is not None:
            self.arena.free(e.arena_key)
            e.arena_offset = None
        elif e.shm is not None:
            e.shm.close()
            e.shm.unlink()
            e.shm = None
        self.used -= e.size
        self.num_spilled += 1

    def _restore(self, oid: bytes) -> bool:
        e = self.objects[oid]
        if not self._evict_for(e.size):
            return False
        data = self.storage.restore(e.spill_path)
        if self.arena is not None:
            e.arena_key = e.arena_key or self._arena_key(oid, e.attempt)
            off = self.arena.alloc(e.arena_key, e.size)
            if off is None or off == -2:
                return False
            memoryview(self._arena_view.buf)[off : off + e.size] = data
            self.arena.seal(e.arena_key)
            e.arena_offset = off
        else:
            shm = ShmSegment(self._shm_name(oid, e.attempt), e.size, create=True)
            shm.buf[:] = data
            e.shm, e.shm_name = shm, shm.name
        self.storage.delete(e.spill_path)
        e.spill_path = ""
        e.state = "SEALED"
        self.used += e.size
        self.num_restored += 1
        return True

    # -- operations (all called on the raylet event loop) --

    def create(self, oid: bytes, size: int, attempt: int = 0,
               owner: str = "") -> dict:
        existing = self.objects.get(oid)
        if existing is not None:
            if attempt < existing.attempt:
                # a newer execution epoch already owns this id: the (zombie)
                # writer must abort without writing or sealing
                return {"status": "stale_attempt", "attempt": existing.attempt}
            if attempt == existing.attempt:
                return {"status": "exists", "state": existing.state}
            # newer attempt replaces the stale copy (seal-once per epoch)
            self._displace(oid, existing)
        if not self._evict_for(size):
            return {"status": "oom", "capacity": self.capacity}
        e = _Entry()
        e.size = size
        e.attempt = attempt
        e.owner = owner
        if self.arena is not None:
            e.arena_key = self._arena_key(oid, attempt)
            off = self.arena.alloc(e.arena_key, size)
            if off == -2:
                # key still quarantined from a displaced copy of this very
                # attempt: the only writer of that epoch is stale — stand down
                return {"status": "stale_attempt", "attempt": attempt}
            if off is None:
                return {"status": "oom", "capacity": self.capacity}
            e.arena_offset = off
            self.objects[oid] = e
            self.used += size
            return {"status": "ok", "arena_name": self.arena_name,
                    "offset": off, "size": size}
        e.shm = ShmSegment(self._shm_name(oid, attempt), size, create=True)
        e.shm_name = e.shm.name
        self.objects[oid] = e
        self.used += size
        return {"status": "ok", "shm_name": e.shm_name}

    def _displace(self, oid: bytes, e: _Entry):
        """Drop a stale-attempt entry so a newer attempt can take the id."""
        del self.objects[oid]
        if e.arena_offset is not None:
            # the stale writer may still hold a client-side mapping into the
            # arena region: quarantine rather than free-and-reuse
            self._quarantine_arena(e.arena_key, e.size)
        elif e.shm is not None:
            self.used -= e.size
            e.shm.close()
            e.shm.unlink()
        if e.spill_path:
            self.storage.delete(e.spill_path)

    def put_inline(self, oid: bytes, blob: bytes, attempt: int = 0,
                   owner: str = "") -> bool:
        existing = self.objects.get(oid)
        if existing is not None:
            if attempt < existing.attempt:
                return False  # stale epoch: rejected
            if attempt == existing.attempt:
                return True  # idempotent
            self._displace(oid, existing)
        e = _Entry()
        e.inline = blob
        e.size = len(blob)
        e.state = "SEALED"
        e.attempt = attempt
        e.owner = owner
        self.objects[oid] = e
        self._wake(oid)
        return True

    def seal(self, oid: bytes, attempt: int = 0) -> bool:
        e = self.objects.get(oid)
        if e is None:
            raise KeyError(f"seal of unknown object {oid.hex()}")
        if e.attempt != attempt:
            return False  # stale writer's seal: fenced off
        e.state = "SEALED"
        e.last_access = time.monotonic()
        self._wake(oid)
        return True

    def _wake(self, oid: bytes):
        for fut in self.waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)

    def contains(self, oid: bytes) -> bool:
        e = self.objects.get(oid)
        return e is not None and e.state in ("SEALED", "SPILLED")

    async def wait_local(self, oid: bytes, timeout: float) -> bool:
        if self.contains(oid):
            return True
        fut = asyncio.get_event_loop().create_future()
        self.waiters.setdefault(oid, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            # cancelled/timed-out waiters must not pile up on oids that
            # never seal (StoreWaitAny cancels these every chunk)
            lst = self.waiters.get(oid)
            if lst is not None:
                try:
                    lst.remove(fut)
                except ValueError:  # raylint: disable=EXC001 waiter already removed by a concurrent seal
                    pass
                if not lst:
                    self.waiters.pop(oid, None)

    def access(self, oid: bytes) -> dict:
        """Local read: returns shm name (restoring from spill) or inline blob."""
        e = self.objects.get(oid)
        if e is None or e.state == "CREATED":
            return {"status": "missing"}
        e.last_access = time.monotonic()
        if e.inline is not None:
            return {"status": "inline", "blob": e.inline}
        if e.state == "SPILLED" and not self._restore(oid):
            return {"status": "oom"}
        if e.arena_offset is not None:
            return {"status": "shm_arena", "arena_name": self.arena_name,
                    "offset": e.arena_offset, "size": e.size}
        return {"status": "shm", "shm_name": e.shm_name, "size": e.size}

    def read_chunk(self, oid: bytes, offset: int, length: int,
                   attempt: Optional[int] = None) -> Optional[bytes]:
        """Remote transfer read path (works for sealed or spilled objects).
        ``attempt`` fences the source: a mid-pull displacement by a newer
        epoch must abort the transfer, not mix epochs in one blob."""
        e = self.objects.get(oid)
        if e is None or e.state == "CREATED":
            return None
        if attempt is not None and e.attempt != attempt:
            return None
        e.last_access = time.monotonic()
        if e.inline is not None:
            return e.inline[offset : offset + length]
        if e.state == "SPILLED":
            return self.storage.restore_range(e.spill_path, offset, length)
        return bytes(self._region(e)[offset : offset + length])

    def object_owner(self, oid: bytes) -> str:
        e = self.objects.get(oid)
        return e.owner if e is not None else ""

    def object_size(self, oid: bytes) -> Optional[int]:
        e = self.objects.get(oid)
        return None if e is None else e.size

    def object_attempt(self, oid: bytes) -> int:
        e = self.objects.get(oid)
        return 0 if e is None else e.attempt

    def write_chunk(self, oid: bytes, offset: int, data: bytes,
                    attempt: int = 0):
        """Pull-side write (store-mediated; remote data lands directly in shm)."""
        e = self.objects.get(oid)
        if e is None or (e.shm is None and e.arena_offset is None):
            raise KeyError(f"write_chunk on missing object {oid.hex()}")
        if e.attempt != attempt:
            raise KeyError(f"write_chunk fenced: {oid.hex()} now at "
                           f"attempt {e.attempt}")
        self._region(e)[offset : offset + len(data)] = data

    def delete(self, oids: List[bytes]):
        for oid in oids:
            e = self.objects.pop(oid, None)
            if e is None:
                continue
            for fut in self.waiters.pop(oid, []):
                if not fut.done():
                    fut.cancel()
            if e.arena_offset is not None:
                self.used -= e.size
                self.arena.free(e.arena_key)
            elif e.shm is not None:
                self.used -= e.size
                e.shm.close()
                e.shm.unlink()
            if e.spill_path:
                self.storage.delete(e.spill_path)

    _ZERO_CHUNK = b"\x00" * (8 * 1024 * 1024)

    def prewarm_step(self, offset: int) -> Optional[int]:
        """Pre-touch one arena chunk at ``offset`` (first-touch /dev/shm
        page faults are ~60x slower than warm writes on some hosts).
        Returns the next offset, or None when done. Runs on the store's
        event loop between awaits, so the live-region check is atomic with
        respect to allocations; chunks overlapping any live entry are
        skipped rather than zeroed."""
        if self._arena_view is None:
            return None
        limit = min(self.capacity, RAY_CONFIG.object_store_prewarm_bytes)
        if offset >= limit:
            return None
        n = min(len(self._ZERO_CHUNK), limit - offset)
        end = offset + n
        for e in self.objects.values():
            if e.arena_offset is not None \
                    and e.arena_offset < end and offset < e.arena_offset + e.size:
                return end  # live data here: skip this chunk
        memoryview(self._arena_view.buf)[offset:end] = self._ZERO_CHUNK[:n]
        return end

    def stats(self) -> dict:
        return {
            "capacity": self.capacity,
            "used": self.used,
            "num_objects": len(self.objects),
            "num_spilled": self.num_spilled,
            "num_restored": self.num_restored,
            "backend": "cpp_arena" if self.arena is not None else "shm_files",
        }

    def shutdown(self):
        self.delete(list(self.objects.keys()))
        if self._arena_view is not None:
            self._arena_view.close()
        if self.arena is not None:
            self.arena.close()
            self.arena = None


# ---------------------------------------------------------------------------
# Client-side segment cache (zero-copy reads keep segments mapped)
# ---------------------------------------------------------------------------


class SegmentCache:
    def __init__(self):
        self._segments: Dict[str, ShmSegment] = {}

    def open(self, name: str) -> ShmSegment:
        seg = self._segments.get(name)
        if seg is None:
            seg = ShmSegment(name)
            self._segments[name] = seg
        return seg

    def clear(self):
        for seg in self._segments.values():
            seg.close()
        self._segments.clear()

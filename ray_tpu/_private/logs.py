"""Per-process logging setup: files under <session>/logs plus stderr.

Reference: python/ray/_private/log_monitor.py + util/logging.cc (rotating
per-process log files under session_latest/logs).
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys


def setup_process_logging(name: str, log_dir: str = "", level=logging.INFO):
    root = logging.getLogger()
    root.setLevel(level)
    fmt = logging.Formatter(
        f"%(asctime)s {name} %(levelname).1s %(name)s: %(message)s", "%H:%M:%S"
    )
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        fh = logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, f"{name}_{os.getpid()}.log"),
            maxBytes=64 * 1024 * 1024,
            backupCount=2,
        )
        fh.setFormatter(fmt)
        root.addHandler(fh)
    sh = logging.StreamHandler(sys.stderr)
    sh.setFormatter(fmt)
    sh.setLevel(logging.WARNING)
    root.addHandler(sh)

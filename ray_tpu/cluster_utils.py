"""Single-machine multi-node cluster harness for tests.

Reference: python/ray/cluster_utils.py (``Cluster`` at :135, ``add_node``
:202, ``remove_node`` :286) — boots one GCS plus N raylets as local
processes, each pretending to be a separate node with its own resources,
labels, and object store, so distributed behavior (node failure, object
transfer, gang scheduling over fake TPU slices) is testable without a
cluster.
"""

from __future__ import annotations

import subprocess
import time
from typing import Dict, List, Optional

from ray_tpu._private.node import NodeSupervisor


class ClusterNode:
    def __init__(self, proc: subprocess.Popen, address: str, node_index: int):
        self.process = proc
        self.address = address
        self.node_index = node_index

    @property
    def node_id(self) -> Optional[str]:
        return getattr(self, "_node_id", None)


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[dict] = None,
        connect: bool = False,
    ):
        self.supervisor: Optional[NodeSupervisor] = None
        self.nodes: List[ClusterNode] = []
        self.gcs_address: Optional[str] = None
        if initialize_head:
            head_args = head_node_args or {}
            self.supervisor = NodeSupervisor(
                resources=head_args.get("resources", {"CPU": 2.0}),
                labels=head_args.get("labels", {}),
                object_store_memory=head_args.get("object_store_memory"),
                gcs_fault_tolerance=head_args.get("gcs_fault_tolerance", False),
            )
            self.gcs_address = self.supervisor.start_head()
            self.nodes.append(ClusterNode(
                self.supervisor.processes[-1], self.supervisor.gcs_address, 0))
        if connect:
            import ray_tpu

            ray_tpu.init(address=self.gcs_address)

    @property
    def address(self) -> str:
        return self.gcs_address

    def add_node(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        num_cpus: Optional[float] = None,
        object_store_memory: Optional[int] = None,
    ) -> ClusterNode:
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        addr = self.supervisor.start_raylet(
            resources=res, labels=labels, object_store_memory=object_store_memory)
        node = ClusterNode(self.supervisor.processes[-1], addr, len(self.nodes))
        self.nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode, allow_graceful: bool = False):
        """Kill a raylet (SIGKILL): simulates node failure. Its workers die
        with it via PR_SET_PDEATHSIG."""
        try:
            if allow_graceful:
                node.process.terminate()
            else:
                node.process.kill()
            node.process.wait(timeout=10.0)
        except Exception:
            pass
        if node in self.nodes:
            self.nodes.remove(node)
        if self.supervisor and node.process in self.supervisor.processes:
            self.supervisor.processes.remove(node.process)

    def wait_for_nodes(self, num_nodes: Optional[int] = None, timeout: float = 30.0):
        """Block until the GCS sees the expected number of alive raylets."""
        import ray_tpu
        from ray_tpu._private import worker as worker_mod

        expect = num_nodes if num_nodes is not None else len(self.nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if worker_mod.is_initialized():
                alive = [n for n in ray_tpu.nodes() if n["alive"]]
                if len(alive) >= expect:
                    return
            time.sleep(0.1)
        raise TimeoutError(f"cluster did not reach {expect} nodes in {timeout}s")

    def kill_gcs(self):
        """Hard-kill the GCS process (requires gcs_fault_tolerance head arg)."""
        self.supervisor.kill_gcs()

    def restart_gcs(self) -> str:
        """Restart the GCS on the same address; it replays persisted tables."""
        return self.supervisor.restart_gcs()

    def shutdown(self):
        if self.supervisor is not None:
            self.supervisor.stop()
            self.supervisor = None
        self.nodes.clear()

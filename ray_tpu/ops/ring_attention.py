"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context sequence/context parallelism is first-class here (the reference
implements none — SURVEY.md §5 — delegating to user code; the TPU framework
provides it natively). Q/K/V live sharded over the ``seq`` mesh axis; each
step computes one block of the online-softmax accumulation and rotates K/V
around the ring with ``ppermute`` — ICI-neighbor traffic only, the canonical
TPU pattern (cf. PAPERS.md ring-attention lineage).

Use inside shard_map (see ray_tpu/parallel/context.py for the wrapper) — the
body is pure jnp + lax collectives, so it is CPU-mesh testable and fuses
under jit on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, q_off, k_off, causal, sm_scale):
    """One (local_q x remote_k) block: returns (scores_exp@v, max, sumexp)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * sm_scale,
                   k.astype(jnp.float32))
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        q_pos = q_off + jnp.arange(sq)[:, None]
        k_pos = k_off + jnp.arange(sk)[None, :]
        s = jnp.where((q_pos >= k_pos)[None, None], s, -1e30)
    m = s.max(axis=-1, keepdims=True)  # (b,h,q,1)
    p = jnp.exp(s - m)
    l = p.sum(axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return pv, m, l


def ring_attention(q, k, v, axis_name: str, causal: bool = True):
    """Exact attention with K/V rotating around `axis_name`.

    Args: q, k, v of local shape (B, S_local, H, D), sharded over seq.
    Returns local (B, S_local, H, D).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    sm_scale = 1.0 / (D ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc = jnp.zeros((B, H, Sq, D), jnp.float32)
    m = jnp.full((B, H, Sq, 1), -1e30, jnp.float32)
    l = jnp.zeros((B, H, Sq, 1), jnp.float32)

    def step(carry, step_idx):
        acc, m, l, k_cur, v_cur = carry
        k_owner = (idx - step_idx) % n
        pv, m_blk, l_blk = _block_attn(
            q, k_cur, v_cur, idx * Sq, k_owner * k_cur.shape[1], causal, sm_scale)
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_blk - m_new)
        acc_new = acc * alpha + pv * beta
        l_new = l * alpha + l_blk * beta
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc_new, m_new, l_new, k_next, v_next), None

    (acc, m, l, _, _), _ = jax.lax.scan(step, (acc, m, l, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True):
    """DeepSpeed-Ulysses style sequence parallelism: all-to-all reshards
    (B, S/n, H, D) -> (B, S, H/n, D), runs full attention on the head shard,
    then reshards back. Requires H % n == 0.
    """
    from ray_tpu.ops.attention import reference_attention

    n = jax.lax.psum(1, axis_name)

    def a2a(x, split_axis, concat_axis):
        return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    qg = a2a(q, 2, 1)  # heads split, seq gathered
    kg = a2a(k, 2, 1)
    vg = a2a(v, 2, 1)
    out = reference_attention(qg, kg, vg, causal=causal)
    return a2a(out, 1, 2)

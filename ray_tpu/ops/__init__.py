"""TPU ops: pallas kernels + jax fallbacks (attention, ring attention, fused)."""

from ray_tpu.ops.attention import attention, flash_attention, reference_attention
from ray_tpu.ops.ring_attention import ring_attention, ulysses_attention

__all__ = [
    "attention",
    "flash_attention",
    "reference_attention",
    "ring_attention",
    "ulysses_attention",
]

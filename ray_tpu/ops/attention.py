"""Attention ops: pallas TPU flash-attention forward + reference path.

The MXU-friendly hot op of the flagship model. The pallas kernel implements
the standard online-softmax flash pattern (one (batch*head, q-block) program,
fori_loop over k-blocks held in VMEM); the backward pass recomputes with the
reference implementation (flash-bwd kernel is a later-round optimization —
rematerialized bwd keeps HBM usage flat at the cost of one extra forward).

CI runs the kernel in pallas interpret mode on CPU (SURVEY.md §4 implication:
every accelerator feature needs a hardware-free tier).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def reference_attention(q, k, v, causal: bool = True,
                        segment_ids: Optional[jax.Array] = None):
    """Pure-XLA attention: (B, S, H, D) -> (B, S, H, D), fp32 softmax."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    S = q.shape[1]
    if causal:
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        scores = jnp.where(seg_mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ---------------------------------------------------------------------------
# pallas flash forward
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q, block_k, causal,
                      sm_scale, seq_len):
    import jax.experimental.pallas as pl

    q = q_ref[0].astype(jnp.float32)  # (block_q, d)
    q_blk = pl.program_id(1)
    d = q.shape[-1]

    nk = seq_len // block_k
    if causal:
        # only k-blocks up to (and including) the diagonal block
        upper = jnp.minimum(((q_blk + 1) * block_q + block_k - 1) // block_k, nk)
    else:
        upper = nk

    def body(i, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = q_blk * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q, 1), -1e30, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention_fwd(q, k, v, causal: bool = True, block_q: int = 128,
                        block_k: int = 128, interpret: bool = False):
    """(B, S, H, D) flash forward via pallas (TPU) / interpret mode (CI)."""
    import jax.experimental.pallas as pl

    B, S, H, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    assert S % block_q == 0 and S % block_k == 0, "seq must divide block sizes"
    sm_scale = 1.0 / (D ** 0.5)
    # (B, S, H, D) -> (B*H, S, D)
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, causal=causal,
        sm_scale=sm_scale, seq_len=S)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = True, interpret: bool = False):
    return flash_attention_fwd(q, k, v, causal=causal, interpret=interpret)


def _fa_fwd(q, k, v, causal, interpret):
    return flash_attention_fwd(q, k, v, causal=causal, interpret=interpret), (q, k, v)


def _fa_bwd(causal, interpret, res, g):
    q, k, v = res
    # rematerialized backward through the reference path (correct, HBM-flat)
    _, vjp = jax.vjp(lambda q_, k_, v_: reference_attention(q_, k_, v_, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def attention(q, k, v, causal: bool = True, impl: str = "auto",
              segment_ids: Optional[jax.Array] = None):
    """Dispatching attention op used by the flagship model."""
    if impl == "auto":
        from ray_tpu.utils import is_tpu

        use_flash = (
            is_tpu()
            and segment_ids is None
            and q.shape[1] % 128 == 0
            and q.shape[-1] in (64, 128, 256)
        )
        impl = "flash" if use_flash else "xla"
    if impl == "flash":
        return flash_attention(q, k, v, causal)
    if impl == "flash_interpret":
        return flash_attention(q, k, v, causal, True)
    return reference_attention(q, k, v, causal, segment_ids)

"""Attention ops: pallas TPU flash-attention forward + reference path.

The MXU-friendly hot op of the flagship model. The pallas kernel implements
the standard online-softmax flash pattern (one (batch*head, q-block) program,
fori_loop over k-blocks held in VMEM); the backward pass recomputes with the
reference implementation (flash-bwd kernel is a later-round optimization —
rematerialized bwd keeps HBM usage flat at the cost of one extra forward).

CI runs the kernel in pallas interpret mode on CPU (SURVEY.md §4 implication:
every accelerator feature needs a hardware-free tier).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def reference_attention(q, k, v, causal: bool = True,
                        segment_ids: Optional[jax.Array] = None):
    """Pure-XLA attention: (B, S, H, D) -> (B, S, H, D), fp32 softmax."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    S = q.shape[1]
    if causal:
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        scores = jnp.where(seg_mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ---------------------------------------------------------------------------
# pallas flash forward
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q,
                      block_k, causal, sm_scale, seq_len):
    import jax.experimental.pallas as pl

    q = q_ref[0].astype(jnp.float32)  # (block_q, d)
    q_blk = pl.program_id(1)
    d = q.shape[-1]

    nk = seq_len // block_k
    if causal:
        # only k-blocks up to (and including) the diagonal block
        upper = jnp.minimum(((q_blk + 1) * block_q + block_k - 1) // block_k, nk)
    else:
        upper = nk

    def body(i, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = q_blk * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q, 1), -1e30, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc, m, l))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # logsumexp per row: the backward's softmax reconstruction key
    # (kept (S, 1)-shaped: TPU blocks need last-two dims 8/128-divisible
    # or full-size, which a trailing singleton satisfies)
    lse_ref[0] = m + jnp.log(l_safe)


def _to_bh(x):
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _from_bh(x, B, H):
    BH, S, D = x.shape
    return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _flash_fwd_impl(q, k, v, causal: bool, interpret: bool,
                    block_q: int = 128, block_k: int = 128):
    """Returns (o, lse) with o in (B, S, H, D) and lse in (B*H, S)."""
    import jax.experimental.pallas as pl

    B, S, H, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    assert S % block_q == 0 and S % block_k == 0, "seq must divide block sizes"
    sm_scale = 1.0 / (D ** 0.5)
    qt, kt, vt = _to_bh(q), _to_bh(k), _to_bh(v)
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, causal=causal,
        sm_scale=sm_scale, seq_len=S)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return _from_bh(out, B, H), lse


def flash_attention_fwd(q, k, v, causal: bool = True, block_q: int = 128,
                        block_k: int = 128, interpret: bool = False):
    """(B, S, H, D) flash forward via pallas (TPU) / interpret mode (CI)."""
    return _flash_fwd_impl(q, k, v, causal, interpret, block_q, block_k)[0]


# ---------------------------------------------------------------------------
# pallas flash backward (FlashAttention-2 style: dQ kernel over k-blocks,
# dK/dV kernel over q-blocks, softmax reconstructed from the saved LSE)
# ---------------------------------------------------------------------------


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_q, block_k, causal, sm_scale,
                         seq_len):
    import jax.experimental.pallas as pl

    q = q_ref[0].astype(jnp.float32)          # (bq, d)
    do = do_ref[0].astype(jnp.float32)        # (bq, d)
    lse = lse_ref[0]                          # (bq, 1)
    delta = delta_ref[0]                      # (bq, 1)
    q_blk = pl.program_id(1)
    nk = seq_len // block_k
    if causal:
        upper = jnp.minimum(((q_blk + 1) * block_q + block_k - 1) // block_k,
                            nk)
    else:
        upper = nk

    def body(i, dq_acc):
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = q_blk * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse)                              # (bq, bk)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        return dq_acc + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, upper, body,
                           jnp.zeros_like(q, dtype=jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q, block_k, causal,
                          sm_scale, seq_len):
    import jax.experimental.pallas as pl

    k = k_ref[0].astype(jnp.float32)          # (bk, d)
    v = v_ref[0].astype(jnp.float32)          # (bk, d)
    k_blk = pl.program_id(1)
    nq = seq_len // block_q
    lower = (k_blk * block_k) // block_q if causal else 0

    def body(i, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_blk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse)                              # (bq, bk)
        dv_acc = dv_acc + jnp.dot(p.T, do,
                                  preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_acc = dk_acc + jnp.dot(ds.T, q,
                                  preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk, dv = jax.lax.fori_loop(
        lower, nq, body,
        (jnp.zeros_like(k, dtype=jnp.float32),
         jnp.zeros_like(v, dtype=jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def flash_attention_bwd(q, k, v, o, lse, g, causal: bool,
                        interpret: bool = False, block_q: int = 128,
                        block_k: int = 128):
    import jax.experimental.pallas as pl

    B, S, H, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    sm_scale = 1.0 / (D ** 0.5)
    qt, kt, vt = _to_bh(q), _to_bh(k), _to_bh(v)
    dot = _to_bh(g)
    # delta = rowsum(dO * O): cheap elementwise — plain XLA, not a kernel
    delta = jnp.sum(dot.astype(jnp.float32)
                    * _to_bh(o).astype(jnp.float32), axis=-1,
                    keepdims=True)  # (B*H, S, 1)
    common = dict(block_q=block_q, block_k=block_k, causal=causal,
                  sm_scale=sm_scale, seq_len=S)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(B * H, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(B * H, S // block_k),
        in_specs=[
            pl.BlockSpec((1, S, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, S, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, S, 1), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, S, 1), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)
    return (_from_bh(dq, B, H), _from_bh(dk, B, H), _from_bh(dv, B, H))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = True, interpret: bool = False):
    return flash_attention_fwd(q, k, v, causal=causal, interpret=interpret)


def _fa_fwd(q, k, v, causal, interpret):
    if _use_pallas_bwd(q.shape[-1]):  # head_dim is static at trace time
        o, lse = _flash_fwd_impl(q, k, v, causal, interpret)
        return o, (q, k, v, o, lse)
    # reference backward never reads o/lse: don't hold them across bwd
    return flash_attention_fwd(q, k, v, causal=causal,
                               interpret=interpret), (q, k, v, None, None)


def _use_pallas_bwd(head_dim: int) -> bool:
    """The pallas backward pair is used for head_dim <= 64 by default: at
    128 the two extra kernels per layer push large programs past the
    tunneled remote-compile helper's limits (empirical; the XLA-recompute
    backward keeps those models compiling). Override with
    RAY_TPU_FLASH_BWD=pallas|reference."""
    import os

    mode = os.environ.get("RAY_TPU_FLASH_BWD", "auto")
    if mode == "pallas":
        return True
    if mode == "reference":
        return False
    return head_dim <= 64


def _fa_bwd(causal, interpret, res, g):
    q, k, v, o, lse = res
    if o is not None:
        return flash_attention_bwd(q, k, v, o, lse, g, causal, interpret)
    # rematerialized backward through the reference path (correct, HBM-flat)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: reference_attention(q_, k_, v_, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def attention(q, k, v, causal: bool = True, impl: str = "auto",
              segment_ids: Optional[jax.Array] = None):
    """Dispatching attention op used by the flagship model."""
    if impl == "auto":
        from ray_tpu.utils import is_tpu

        use_flash = (
            is_tpu()
            and segment_ids is None
            and q.shape[1] % 128 == 0
            and q.shape[-1] in (64, 128, 256)
        )
        impl = "flash" if use_flash else "xla"
    if impl == "flash":
        return flash_attention(q, k, v, causal)
    if impl == "flash_interpret":
        return flash_attention(q, k, v, causal, True)
    return reference_attention(q, k, v, causal, segment_ids)

"""Job submission: SDK + supervisor actors (reference: dashboard/modules/job).

``JobSubmissionClient.submit_job`` (reference: job/sdk.py:126) starts a
detached ``JobSupervisor`` actor (reference: job_supervisor.py) that runs
the entrypoint command as a subprocess wired to this cluster
(``RAY_TPU_ADDRESS``), applies the job's runtime_env (env vars + extracted
working_dir as the subprocess cwd), captures combined output, and records
status + logs in GCS KV so any client can poll them. The dashboard-lite
HTTP server exposes the same operations over REST.
"""

from ray_tpu.job.job_manager import (
    JobStatus,
    JobSubmissionClient,
)

__all__ = ["JobSubmissionClient", "JobStatus"]

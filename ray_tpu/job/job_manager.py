"""Job manager: supervisor actor per job + KV-backed status/log store.

Reference: dashboard/modules/job/{job_manager.py,job_supervisor.py,sdk.py}.
KV schema (GCS): ns="job" key=<submission_id> -> wire-encoded info dict;
ns="job_logs" key=<submission_id> -> utf-8 log bytes (flushed periodically
and at exit by the supervisor).
"""

from __future__ import annotations

import os
from ray_tpu._private import wire
import subprocess
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


def _kv_call(method: str, req: dict):
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    return core._run(core._gcs_call(method, req))


def _job_put(submission_id: str, info: dict):
    _kv_call("KVPut", {"ns": "job", "key": submission_id,
                       "value": wire.dumps(info)})


def _job_get(submission_id: str) -> Optional[dict]:
    blob = _kv_call("KVGet", {"ns": "job", "key": submission_id})["value"]
    return wire.loads(blob) if blob is not None else None


@ray_tpu.remote(num_cpus=0.1, max_restarts=0)
class JobSupervisor:
    """Runs one job entrypoint as a subprocess; owns its lifecycle."""

    def __init__(self, submission_id: str, entrypoint: str,
                 runtime_env: Optional[dict], metadata: Optional[dict]):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env
        self.metadata = metadata or {}
        self.proc: Optional[subprocess.Popen] = None
        self._stopped = False

    def _update(self, **fields):
        info = _job_get(self.submission_id) or {}
        info.update(fields)
        _job_put(self.submission_id, info)

    def _flush_logs(self, path: str):
        try:
            with open(path, "rb") as f:
                _kv_call("KVPut", {"ns": "job_logs", "key": self.submission_id,
                                   "value": f.read()})
        except FileNotFoundError:
            pass

    def run(self) -> str:
        """Blocking: runs the entrypoint to completion; returns final state."""
        from ray_tpu._private import runtime_env as renv_mod
        from ray_tpu._private.worker import global_worker

        core = global_worker()
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = core.gcs_address
        env["RAY_TPU_JOB_SUBMISSION_ID"] = self.submission_id
        # the entrypoint must be able to import this framework even after
        # chdir into its working_dir (reference: ray injects itself)
        import ray_tpu as _pkg

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
            _pkg.__file__)))
        extra_paths = [pkg_root]
        cwd = None
        renv = self.runtime_env
        if renv:
            env.update(renv.get("env_vars") or {})

            def kv_get(key):
                return _kv_call("KVGet", {"ns": "renv", "key": key})["value"]

            wd = renv.get("working_dir")
            if wd:
                cwd = renv_mod._extract(wd, kv_get)
            extra_paths = [renv_mod._extract(p, kv_get)
                           for p in renv.get("py_modules") or []] + extra_paths
            if cwd:
                extra_paths.insert(0, cwd)
        env["PYTHONPATH"] = ":".join(
            extra_paths + [env.get("PYTHONPATH", "")]).rstrip(":")

        log_path = f"/tmp/ray_tpu_job_{self.submission_id}.log"
        self._update(status=JobStatus.RUNNING, start_time=time.time())
        with open(log_path, "wb") as logf:
            self.proc = subprocess.Popen(
                self.entrypoint, shell=True, cwd=cwd, env=env,
                stdout=logf, stderr=subprocess.STDOUT)
            last_flush = 0.0
            while self.proc.poll() is None:
                time.sleep(0.2)
                if time.monotonic() - last_flush > 2.0:
                    self._flush_logs(log_path)
                    last_flush = time.monotonic()
        self._flush_logs(log_path)
        code = self.proc.returncode
        if self._stopped:
            state = JobStatus.STOPPED
        elif code == 0:
            state = JobStatus.SUCCEEDED
        else:
            state = JobStatus.FAILED
        self._update(status=state, end_time=time.time(), exit_code=code,
                     message=f"exit code {code}")
        return state

    def stop(self) -> bool:
        self._stopped = True
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            return True
        return False

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """SDK entry point (reference: dashboard/modules/job/sdk.py:36).

    Talks to the cluster through the driver's GCS connection; ``address``
    may be a GCS address or None to use the already-initialized driver /
    RAY_TPU_ADDRESS.
    """

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address, log_to_driver=False,
                         ignore_reinit_error=True)

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        from ray_tpu._private.worker import global_worker

        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if _job_get(submission_id) is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        core = global_worker()
        prepared = core._run(core._prepare_runtime_env(runtime_env)) \
            if runtime_env else None
        _job_put(submission_id, {
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "status": JobStatus.PENDING,
            "submit_time": time.time(),
            "metadata": metadata or {},
        })
        # max_concurrency > 1: run() blocks for the whole job, stop()/ping()
        # must still get through (reference: async JobSupervisor)
        supervisor = JobSupervisor.options(
            name=f"_job_supervisor:{submission_id}", lifetime="detached",
            num_cpus=0.1, max_concurrency=4,
        ).remote(submission_id, entrypoint, prepared, metadata)
        supervisor.run.remote()  # fire-and-forget; status lands in KV
        return submission_id

    def get_job_status(self, submission_id: str) -> str:
        info = _job_get(submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        return info["status"]

    def get_job_info(self, submission_id: str) -> dict:
        info = _job_get(submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        return info

    def list_jobs(self) -> List[dict]:
        keys = _kv_call("KVKeys", {"ns": "job", "prefix": ""})["keys"]
        return [i for i in (_job_get(k) for k in keys) if i is not None]

    def get_job_logs(self, submission_id: str) -> str:
        blob = _kv_call("KVGet", {"ns": "job_logs",
                                  "key": submission_id})["value"]
        return (blob or b"").decode(errors="replace")

    def stop_job(self, submission_id: str) -> bool:
        try:
            sup = ray_tpu.get_actor(f"_job_supervisor:{submission_id}")
        except ValueError:
            return False
        return ray_tpu.get(sup.stop.remote(), timeout=30)

    def delete_job(self, submission_id: str) -> bool:
        info = _job_get(submission_id)
        if info is None:
            return False
        if info["status"] not in JobStatus.TERMINAL:
            raise RuntimeError("job is still running; stop it first")
        _kv_call("KVDel", {"ns": "job", "key": submission_id})
        _kv_call("KVDel", {"ns": "job_logs", "key": submission_id})
        return True

    def wait_until_finished(self, submission_id: str, timeout: float = 300.0,
                            poll_s: float = 0.5) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(poll_s)
        raise TimeoutError(
            f"job {submission_id} not finished after {timeout}s")

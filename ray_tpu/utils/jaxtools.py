"""Central jax import: honors RAY_TPU_JAX_PLATFORMS before backends init.

Some environments force a platform plugin (e.g. a tunneled TPU) regardless of
``JAX_PLATFORMS``; the test tier must still run on a virtual CPU mesh. Every
framework module that needs jax goes through :func:`import_jax`, which applies
the ``RAY_TPU_JAX_PLATFORMS`` override via ``jax.config`` exactly once, before
any backend is initialized.
"""

from __future__ import annotations

import os

_applied = False


def jax_platform_forced() -> str:
    return os.environ.get("RAY_TPU_JAX_PLATFORMS", "")


def import_jax():
    global _applied
    import jax

    if not _applied:
        plat = jax_platform_forced()
        if plat:
            try:
                jax.config.update("jax_platforms", plat)
            except Exception:
                pass
        _applied = True
    return jax

"""Framework utilities (jax bootstrap, timing, tree helpers)."""

from ray_tpu.utils.jaxtools import import_jax, jax_platform_forced

__all__ = ["import_jax", "jax_platform_forced", "is_tpu"]


def is_tpu() -> bool:
    """True when jax runs on TPU hardware, including plugin backends whose
    platform name differs (e.g. a tunneled dev chip): detect by device kind,
    not backend name. Single source of truth for bench + kernel dispatch."""
    import jax

    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    return (jax.default_backend() == "tpu"
            or "tpu" in str(getattr(dev, "platform", "")).lower()
            or "tpu" in str(getattr(dev, "device_kind", "")).lower()
            or "tpu" in str(dev).lower())

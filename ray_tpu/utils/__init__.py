"""Framework utilities (jax bootstrap, timing, tree helpers)."""

from ray_tpu.utils.jaxtools import import_jax, jax_platform_forced

__all__ = ["import_jax", "jax_platform_forced"]

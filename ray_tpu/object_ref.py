"""ObjectRef: a future for a value in the distributed object store.

Reference: ObjectRef in python/ray/includes/object_ref.pxi — an id plus owner
metadata; values are resolved with ``ray_tpu.get``.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID

# process-wide reference counter hook, installed by the connected CoreWorker
# (reference: reference_counter.cc tracks every handle's lifetime)
_ref_counter = None


def set_ref_counter(rc) -> None:
    global _ref_counter
    _ref_counter = rc


class ObjectRef:
    __slots__ = ("_id", "_owner_address", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str = ""):
        self._id = object_id
        self._owner_address = owner_address
        rc = _ref_counter
        if rc is not None:
            rc.ref_created(object_id.binary(), owner_address)

    def __del__(self):
        rc = _ref_counter
        if rc is not None:
            try:
                rc.ref_deleted(self._id.binary())
            except Exception:
                pass  # interpreter teardown

    @property
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def owner_address(self) -> str:
        return self._owner_address

    def task_id(self):
        return self._id.task_id()

    def future(self):
        """concurrent.futures.Future resolving to the value."""
        from ray_tpu._private import worker as _worker

        return _worker.global_worker().as_future(self)

    def __reduce__(self):
        lst = getattr(_serialized_refs, "refs", None)
        if lst is not None:
            lst.append((self._id.binary(), self._owner_address))
        return (_rebuild_ref, (self._id.binary(), self._owner_address))

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __await__(self):
        from ray_tpu._private import worker as _worker

        return _worker.global_worker().await_ref(self).__await__()


def _rebuild_ref(id_bytes: bytes, owner_address: str) -> ObjectRef:
    lst = getattr(_deserialized_refs, "refs", None)
    if lst is not None:
        lst.append((id_bytes, owner_address))
    return ObjectRef(ObjectID(id_bytes), owner_address)


# thread-local collector: while active, every ObjectRef serialized on this
# thread is recorded so the caller can pin/track contained (nested) refs
import contextlib as _contextlib
import threading as _threading

_serialized_refs = _threading.local()


@_contextlib.contextmanager
def collect_serialized_refs():
    prev = getattr(_serialized_refs, "refs", None)
    out: list = []
    _serialized_refs.refs = out
    try:
        yield out
    finally:
        _serialized_refs.refs = prev


_deserialized_refs = _threading.local()


@_contextlib.contextmanager
def collect_deserialized_refs():
    """Record every ObjectRef rebuilt from a pickle on this thread — used by
    executors to learn which foreign refs a task received (borrow tracking)."""
    prev = getattr(_deserialized_refs, "refs", None)
    out: list = []
    _deserialized_refs.refs = out
    try:
        yield out
    finally:
        _deserialized_refs.refs = prev


class ObjectRefGenerator:
    """Iterator over a streaming task's dynamically-created return refs
    (reference: ray.ObjectRefGenerator for num_returns="streaming",
    generator_waiter.cc). Each __next__ blocks until the executor has
    streamed the next yield to the owner, then hands back its ObjectRef;
    exhausts with StopIteration when the generator completes."""

    def __init__(self, core, task_id, owner_address: str):
        self._core = core
        self._task_id = task_id
        self._owner_address = owner_address
        self._index = 0

    def __iter__(self):
        return self

    def __next__(self):
        ref = self._core.stream_next(self._task_id, self._index)
        self._index += 1
        return ref

    def __repr__(self):
        return (f"ObjectRefGenerator(task={self._task_id.hex()[:12]}, "
                f"next_index={self._index})")

    def __del__(self):
        # release arrival pins for items never consumed (lock-based, safe
        # from GC on any thread)
        try:
            self._core.stream_release(self._task_id)
        except Exception:
            pass

"""ObjectRef: a future for a value in the distributed object store.

Reference: ObjectRef in python/ray/includes/object_ref.pxi — an id plus owner
metadata; values are resolved with ``ray_tpu.get``.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner_address", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str = ""):
        self._id = object_id
        self._owner_address = owner_address

    @property
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def owner_address(self) -> str:
        return self._owner_address

    def task_id(self):
        return self._id.task_id()

    def future(self):
        """concurrent.futures.Future resolving to the value."""
        from ray_tpu._private import worker as _worker

        return _worker.global_worker().as_future(self)

    def __reduce__(self):
        return (_rebuild_ref, (self._id.binary(), self._owner_address))

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __await__(self):
        from ray_tpu._private import worker as _worker

        return _worker.global_worker().await_ref(self).__await__()


def _rebuild_ref(id_bytes: bytes, owner_address: str) -> ObjectRef:
    return ObjectRef(ObjectID(id_bytes), owner_address)

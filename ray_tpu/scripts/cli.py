"""CLI: cluster lifecycle + state inspection + microbenchmark.

Reference: python/ray/scripts/scripts.py (`ray start/stop/status/...`,
`ray microbenchmark`, `ray list ...` via util/state/state_cli.py).

Usage: python -m ray_tpu.scripts.cli <command> [...]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def cmd_start(args):
    """Start a head node that outlives this command (ray start --head)."""
    from ray_tpu._private.node import NodeSupervisor

    resources = json.loads(args.resources) if args.resources else {}
    if args.num_cpus is not None:
        resources["CPU"] = float(args.num_cpus)
    supervisor = NodeSupervisor(resources=resources,
                                labels=json.loads(args.labels or "{}"))
    address = supervisor.start_head()
    with open(args.address_file, "w") as f:
        f.write(address)
    print(f"head started; GCS at {address} (address file: {args.address_file})")
    if args.include_dashboard:
        dash = supervisor.start_dashboard(port=args.dashboard_port)
        print(f"dashboard at http://{dash}")
    if args.client_server_port:
        import threading

        import ray_tpu
        from ray_tpu.util.client import start_client_server

        ray_tpu.init(address=address)

        def _serve_clients():
            try:
                start_client_server(port=args.client_server_port)
            except BaseException as e:  # surface bind failures
                print(f"client server FAILED: {e}", file=sys.stderr)

        threading.Thread(target=_serve_clients, daemon=True).start()
        print(f"client endpoint: ray-tpu://<this-host>:{args.client_server_port} "
              "(watch for the 'listening on' line)")
    print("press Ctrl-C to stop")
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    supervisor.stop()


def _connect(args):
    import ray_tpu

    address = args.address
    if not address and os.path.exists(args.address_file):
        address = open(args.address_file).read().strip()
    if not address:
        print("no --address given and no address file found", file=sys.stderr)
        sys.exit(1)
    ray_tpu.init(address=address)
    return ray_tpu


def _status_payload():
    """Fleet summary + per-job goodput column: the status view answers
    "is the cluster healthy AND are the jobs on it productive" without a
    second command. Goodput is best-effort — a cluster with no tagged
    jobs (or a pre-goodput GCS) just shows an empty column."""
    from ray_tpu.util.state import goodput, summarize_cluster

    out = summarize_cluster()
    try:
        out["goodput"] = {
            name: round(float(view.get("goodput_fraction", 0.0)), 4)
            for name, view in sorted(goodput().items())}
    except Exception:
        out["goodput"] = {}
    return out


def cmd_status(args):
    _connect(args)
    print(json.dumps(_status_payload(), indent=2))


def cmd_list(args):
    _connect(args)
    from ray_tpu.util import state

    fn = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "jobs": state.list_jobs,
        "placement-groups": state.list_placement_groups,
    }[args.what]
    print(json.dumps(fn(), indent=2, default=str))


def cmd_job(args):
    """ray job submit/status/logs/list/stop (reference: job CLI in
    dashboard/modules/job/cli.py)."""
    _connect(args)
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient()
    if args.job_command == "submit":
        renv = json.loads(args.runtime_env) if args.runtime_env else None
        sid = client.submit_job(entrypoint=" ".join(args.entrypoint),
                                runtime_env=renv)
        print(sid)
        if args.wait:
            status = client.wait_until_finished(sid, timeout=args.timeout)
            print(status)
            print(client.get_job_logs(sid), end="")
            sys.exit(0 if status == "SUCCEEDED" else 1)
    elif args.job_command == "status":
        print(client.get_job_status(args.submission_id))
    elif args.job_command == "logs":
        print(client.get_job_logs(args.submission_id), end="")
    elif args.job_command == "list":
        print(json.dumps(client.list_jobs(), indent=2, default=str))
    elif args.job_command == "stop":
        print(client.stop_job(args.submission_id))


def cmd_timeline(args):
    """ray-tpu timeline: export a chrome://tracing JSON of task spans
    (reference: `ray timeline`). ``--from-gcs`` renders the task flow
    graph from the GCS task-event ring instead (works without tracing
    enabled; same payload as ``GET /api/timeline``)."""
    _connect(args)

    if args.from_gcs:
        from ray_tpu.util.state import get_timeline

        trace = get_timeline(job_id=args.job_id or None)
        with open(args.out, "w") as f:
            json.dump(trace, f)
        n = len(trace["traceEvents"])
    else:
        from ray_tpu.util import tracing

        n = tracing.export_chrome_trace(args.out)
    print(f"wrote {n} events to {args.out} (open in chrome://tracing)")


def cmd_health(args):
    """ray-tpu health: the GCS cluster-health report (stuck tasks,
    straggler nodes, dead-zygote/pool starvation)."""
    _connect(args)
    import time as _t

    from ray_tpu.util.state import cluster_health

    health = cluster_health(scan=args.scan)
    if args.json:
        print(json.dumps(health, indent=2, default=str))
        return
    ts = _t.strftime("%H:%M:%S", _t.localtime(health.get("ts", 0)))
    print(f"[{health.get('status', 'unknown').upper()}] scanned {ts} "
          f"(scan #{health.get('scan_count', 0)}, every "
          f"{health.get('scan_interval_s', 0):g}s, "
          f"{health.get('nodes_alive', 0)} nodes alive)")
    for f in health.get("findings", []):
        detail = " ".join(f"{k}={v}" for k, v in f.items()
                          if k not in ("kind", "severity"))
        print(f"  {f['severity']:7} {f['kind']}: {detail}")
    if not health.get("findings"):
        print("  no findings")


def cmd_goodput(args):
    """ray-tpu goodput: per-job wall-clock attribution ledgers — where
    each job's seconds went (step_compute, collective_wait, input_stall,
    ckpt_pause, compile, reform_downtime, bubble, overhead, idle) plus
    the derived goodput_fraction; same payload as ``GET /api/goodput``."""
    _connect(args)
    from ray_tpu.util.state import goodput as state_goodput

    jobs = state_goodput(job=args.job or None)
    if args.json:
        print(json.dumps(jobs, indent=2, default=str))
        return
    if not jobs:
        print("no goodput ledgers (no tagged jobs have reported yet)")
        return
    for name, view in jobs.items():
        wall = view.get("wall_s", 0.0)
        frac = view.get("goodput_fraction", 0.0)
        mfu = view.get("mfu")
        head = (f"{name}: wall {wall:.1f}s  goodput {frac:.1%}  "
                f"procs {view.get('fresh_procs', 0)}/{view.get('procs', 0)}")
        if mfu is not None:
            head += f"  mfu {mfu:.3f}"
        print(head)
        buckets = view.get("buckets", {})
        for bucket, secs in sorted(buckets.items(),
                                   key=lambda kv: -kv[1]):
            if secs <= 0:
                continue
            share = secs / wall if wall > 0 else 0.0
            print(f"  {bucket:16} {secs:10.2f}s  {share:6.1%}")
        counters = view.get("counters", {})
        if counters:
            print("  counters: " + " ".join(
                f"{k}={counters[k]:g}" for k in sorted(counters)))


def cmd_events(args):
    """ray-tpu events: recent structured cluster events (reference: the
    export-event pipeline surfaced by the dashboard aggregator)."""
    _connect(args)
    import time as _t

    from ray_tpu.util import events as events_mod

    for e in events_mod.list_events(source=args.source or None,
                                    severity=args.severity or None,
                                    limit=args.limit):
        ts = _t.strftime("%H:%M:%S", _t.localtime(e.get("ts", 0)))
        meta = " ".join(f"{k}={v}" for k, v in (e.get("metadata") or {}).items())
        print(f"{ts} [{e.get('severity')}] {e.get('source')}: "
              f"{e.get('message')} {meta}")


def cmd_tasks(args):
    """ray-tpu tasks: task lifecycle records and the `ray summary tasks`
    analog (reference: `ray list tasks` / `ray summary tasks` backed by
    GcsTaskManager)."""
    _connect(args)
    import time as _t

    from ray_tpu.util import state

    if args.summary:
        print(json.dumps(state.summarize_tasks(), indent=2))
        return
    if args.task_id:
        print(json.dumps(state.get_task(args.task_id), indent=2, default=str))
        return
    tasks = state.list_tasks(name=args.name or None,
                             state_filter=args.state or None,
                             limit=args.limit)
    for t in tasks:
        start = _t.strftime("%H:%M:%S",
                            _t.localtime(t.get("start_ts", 0)))
        transitions = "->".join(e["state"] for e in t.get("events", []))
        err = f" err={t['error']!r}" if t.get("error") else ""
        print(f"{start} {t['task_id'][:16]} {t['name'] or '?':32} "
              f"[{t['state']}] attempt={t['attempt']} {transitions}{err}")


def cmd_workers(args):
    """ray-tpu workers: per-node worker-pool / provisioning-plane stats
    (reference surface: the dashboard's /api/workers; backed by the KV
    mirror each raylet's metrics loop publishes)."""
    _connect(args)
    from ray_tpu.util import state

    pools = state.list_worker_pools()
    if args.json:
        print(json.dumps(pools, indent=2, default=str))
        return
    for key, entry in sorted(pools.items()):
        p = entry.get("pool", {})
        zyg = "zygote=up" if p.get("zygote_alive") else (
            "zygote=DOWN" if p.get("enabled") else "zygote=off")
        print(f"{entry.get('node', key)[:12]} {zyg} "
              f"warm={p.get('warm_default_env', 0)}/{p.get('warm_target', 0)} "
              f"workers={p.get('total_workers', 0)} "
              f"hits={p.get('hits', 0)} misses={p.get('misses', 0)} "
              f"forks={p.get('forks', 0)} cold={p.get('cold_spawns', 0)} "
              f"restarts={p.get('zygote_restarts', 0)}")


def cmd_serve(args):
    """ray-tpu serve: serve autoscale-plane state per deployment
    (reference surface: the dashboard's /api/serve; backed by the KV
    mirror the serve controller publishes every autoscale tick)."""
    _connect(args)
    import time as _t

    from ray_tpu.util import state

    deployments = state.serve_state()
    if args.json:
        print(json.dumps(deployments, indent=2, default=str))
        return
    if not deployments:
        print("no serve deployments")
        return
    for name, entry in sorted(deployments.items()):
        rollup = entry.get("rollup") or {}
        qp99 = rollup.get("queue_p99_s")
        slo = entry.get("slo") or {}
        print(f"{name}: replicas={entry.get('replicas', 0)}/"
              f"{entry.get('target', 0)} "
              f"draining={entry.get('draining', 0)} "
              f"arrival={rollup.get('arrival_rate', 0.0):.2f}/s "
              f"queue_p99={'n/a' if qp99 is None else '%.3fs' % qp99}"
              + (f" slo(queue)={slo.get('queue_target_s')}s"
                 if slo.get("queue_target_s") is not None else ""))
        for tr in entry.get("transitions", [])[-args.transitions:]:
            ts = _t.strftime("%H:%M:%S", _t.localtime(tr.get("ts", 0)))
            print(f"  {ts} scale {tr.get('direction', '?'):4} "
                  f"{tr.get('from')}->{tr.get('to')}: {tr.get('reason')}")


def cmd_ckpt(args):
    """ray-tpu ckpt: inspect and manage checkpoint-plane stores
    (ray_tpu/ckpt/).

    With ``--root`` the subcommands operate directly on a store directory
    (no cluster needed); without it, ``list`` shows every store registered
    with the GCS (KV ns ``ckpt``). ``mirror``/``evict``/``verify`` drive
    a tiered store's remote tier through its persisted TIER descriptor."""
    if args.ckpt_command == "sweep":
        # cluster-side: force the GCS retention sweeper over every
        # registered store now and print its reports
        _connect(args)
        from ray_tpu._private import worker as worker_mod

        core = worker_mod.global_worker()
        out = core._run(core._gcs_call("CkptSweep", {}), 300.0)
        print(json.dumps(out["reports"], indent=2, default=str))
        return
    if not args.root:
        _connect(args)
        from ray_tpu.util.state import list_checkpoints

        print(json.dumps(list_checkpoints(), indent=2, default=str))
        return
    from ray_tpu.ckpt import CheckpointStore, diff_manifests

    if args.ckpt_command in ("mirror", "evict", "verify"):
        from ray_tpu.ckpt.tier import attach

        tiered = attach(args.root, mirror=False)
        ckpt_id = getattr(args, "ckpt_id", "") or None
        if args.ckpt_command == "mirror":
            out = tiered.mirror_now(ckpt_id)
        elif args.ckpt_command == "evict":
            out = tiered.evict_local(ckpt_id or tiered.latest_id())
        else:
            out = tiered.verify(ckpt_id, deep=args.deep)
        print(json.dumps(out, indent=2, default=str))
        if args.ckpt_command == "verify" and not out.get("ok"):
            sys.exit(1)
        return

    store = CheckpointStore(args.root)
    if args.ckpt_command == "list":
        rows = store.stats()
        print(json.dumps(rows, indent=2, default=str))
    elif args.ckpt_command == "inspect":
        man = store.read(args.ckpt_id) if args.ckpt_id else store.latest()
        if man is None:
            print("no committed checkpoint", file=sys.stderr)
            sys.exit(1)
        out = man.to_json()
        if not args.chunks:
            # per-leaf chunk lists are the bulk of a big manifest; show
            # counts unless asked
            out["leaves"] = {
                k: {"kind": v["kind"], "shape": v["shape"],
                    "dtype": v["dtype"], "num_chunks": len(v["chunks"])}
                for k, v in out["leaves"].items()}
        print(json.dumps(out, indent=2, default=str))
    elif args.ckpt_command == "diff":
        print(json.dumps(diff_manifests(store.read(args.a),
                                        store.read(args.b)),
                         indent=2, default=str))


def cmd_microbenchmark(args):
    import ray_tpu

    if args.address or os.path.exists(args.address_file):
        _connect(args)
    else:
        ray_tpu.init(num_cpus=args.num_cpus or None)
    from ray_tpu._private.microbenchmark import main as bench_main

    for row in bench_main(duration=args.duration):
        print(json.dumps(row))
    ray_tpu.shutdown()


def cmd_stack(args):
    """`ray-tpu stack` (reference: `ray stack` / dashboard py-spy): sample a
    worker's call stacks, or take a tracemalloc memory snapshot."""
    _connect(args)
    from ray_tpu.util.state import get_node_stats, list_nodes, profile_worker

    nodes = [n for n in list_nodes() if n["alive"]]
    node = next((n for n in nodes
                 if n["node_id"].startswith(args.node or "")), None)
    if node is None:
        print(f"no node matching {args.node!r}")
        return
    if args.pid is None:
        stats = get_node_stats(node["address"], agent=True)
        for w in stats["agent"]["workers"]:
            print(json.dumps(w))
        return
    if args.memory:
        out = profile_worker(node["address"], args.pid, kind="memory",
                             action=args.memory_action)
    else:
        out = profile_worker(node["address"], args.pid, kind="stacks",
                             duration_s=args.duration)
    print(json.dumps(out.get("profile", out), indent=1))


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray-tpu")
    parser.add_argument("--address", default="")
    parser.add_argument("--address-file", default="/tmp/ray_tpu_sessions/head_address")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start", help="start a head node")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--resources", default="")
    p.add_argument("--labels", default="")
    p.add_argument("--include-dashboard", action="store_true")
    p.add_argument("--dashboard-port", type=int, default=8265)
    p.add_argument("--client-server-port", type=int, default=0,
                   help="serve a ray-tpu:// client endpoint on this port")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status", help="cluster summary")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list", help="list cluster entities")
    p.add_argument("what", choices=["nodes", "actors", "jobs", "placement-groups"])
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("job", help="submit and manage jobs")
    jsub = p.add_subparsers(dest="job_command", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--runtime-env", default="", help="JSON runtime env")
    js.add_argument("--wait", action="store_true")
    js.add_argument("--timeout", type=float, default=600.0)
    js.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("submission_id")
    jsub.add_parser("list")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("timeline", help="export chrome://tracing task timeline")
    p.add_argument("--out", default="timeline.json")
    p.add_argument("--from-gcs", action="store_true",
                   help="render from the GCS task-event ring (no tracing "
                        "needed) instead of the span table")
    p.add_argument("--job-id", default="", help="filter by job (with --from-gcs)")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("health", help="cluster-health report "
                                      "(stuck/straggler/pool findings)")
    p.add_argument("--scan", action="store_true",
                   help="force a scan now instead of the last periodic one")
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_health)

    p = sub.add_parser("goodput", help="per-job goodput ledgers "
                                       "(wall-clock attribution buckets)")
    p.add_argument("--job", default="", help="filter to one run name")
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_goodput)

    p = sub.add_parser("events", help="recent structured cluster events")
    p.add_argument("--source", default="")
    p.add_argument("--severity", default="")
    p.add_argument("--limit", type=int, default=100)
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("tasks", help="task lifecycle records / summary")
    p.add_argument("--summary", action="store_true",
                   help="per-function counts by state (ray summary tasks)")
    p.add_argument("--task-id", default="", help="one task's full record")
    p.add_argument("--name", default="", help="filter by function name")
    p.add_argument("--state", default="", help="filter by lifecycle state")
    p.add_argument("--limit", type=int, default=100)
    p.set_defaults(fn=cmd_tasks)

    p = sub.add_parser("workers", help="per-node worker-pool / "
                                       "provisioning-plane stats")
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_workers)

    p = sub.add_parser("serve", help="serve autoscale-plane state "
                                     "(replicas, rates, scale history)")
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.add_argument("--transitions", type=int, default=4,
                   help="scale transitions to show per deployment")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("ckpt", help="checkpoint-plane stores "
                                    "(list/inspect/diff)")
    csub = p.add_subparsers(dest="ckpt_command", required=False)
    cl = csub.add_parser("list", help="store summary (all registered "
                                      "stores without --root)")
    cl.add_argument("--root", default="", help="store directory")
    ci = csub.add_parser("inspect", help="one manifest (default: latest)")
    ci.add_argument("--root", required=True)
    ci.add_argument("ckpt_id", nargs="?", default="")
    ci.add_argument("--chunks", action="store_true",
                    help="include full per-leaf chunk lists")
    cd = csub.add_parser("diff", help="chunk delta between two manifests")
    cd.add_argument("--root", required=True)
    cd.add_argument("a")
    cd.add_argument("b")
    cm = csub.add_parser("mirror", help="replicate a checkpoint to the "
                                        "store's remote tier now")
    cm.add_argument("--root", required=True)
    cm.add_argument("ckpt_id", nargs="?", default="")
    ce = csub.add_parser("evict", help="drop local chunk bytes of a "
                                       "fully-mirrored checkpoint")
    ce.add_argument("--root", required=True)
    ce.add_argument("ckpt_id", nargs="?", default="")
    cv = csub.add_parser("verify", help="check a checkpoint's remote "
                                        "durability (exit 1 if not ok)")
    cv.add_argument("--root", required=True)
    cv.add_argument("ckpt_id", nargs="?", default="")
    cv.add_argument("--deep", action="store_true",
                    help="fetch and sha256-verify every chunk")
    cs = csub.add_parser("sweep", help="force the GCS retention sweeper "
                                       "over every registered store now")
    cs.add_argument("--root", default="")  # unused; uniform surface
    p.set_defaults(fn=cmd_ckpt, ckpt_command="list", root="")

    p = sub.add_parser("microbenchmark", help="run the core perf suite")
    p.add_argument("--duration", type=float, default=2.0)
    p.add_argument("--num-cpus", type=float, default=None)
    p.set_defaults(fn=cmd_microbenchmark)

    p = sub.add_parser("stack", help="profile a worker (stacks or memory)")
    p.add_argument("--node", default="", help="node id prefix (default: head)")
    p.add_argument("--pid", type=int, default=None,
                   help="worker pid (omit to list workers)")
    p.add_argument("--duration", type=float, default=2.0)
    p.add_argument("--memory", action="store_true")
    p.add_argument("--memory-action", default="snapshot",
                   choices=["start", "snapshot", "stop"])
    p.set_defaults(fn=cmd_stack)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()

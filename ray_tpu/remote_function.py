"""@ray_tpu.remote on functions (reference: python/ray/remote_function.py)."""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict

from ray_tpu._private.common import TaskOptions


_OPTION_FIELDS = set(TaskOptions.__dataclass_fields__)


def build_task_options(defaults: TaskOptions, overrides: Dict[str, Any]) -> TaskOptions:
    opts = copy.copy(defaults)
    for key, value in overrides.items():
        if key == "scheduling_strategy":
            opts.scheduling_strategy = value
        elif key in _OPTION_FIELDS:
            setattr(opts, key, value)
        else:
            raise ValueError(f"unknown option {key!r}")
    # a PlacementGroupSchedulingStrategy implies the pg fields
    strat = opts.scheduling_strategy
    if strat is not None and hasattr(strat, "placement_group"):
        opts.placement_group = strat.placement_group
        opts.placement_group_bundle_index = getattr(
            strat, "placement_group_bundle_index", -1
        )
    if opts.runtime_env:
        # validate HERE (decoration / .options() time), once — not per
        # .remote() in the submit hot loop; invalid envs raise to the user
        from ray_tpu._private import runtime_env as renv_mod

        opts.runtime_env = renv_mod.normalize(opts.runtime_env)
    return opts


class RemoteFunction:
    def __init__(self, function: Callable, options: TaskOptions):
        self._function = function
        self._options = options
        self._function_name = getattr(function, "__qualname__", repr(function))
        self.__doc__ = function.__doc__

    @property
    def function(self) -> Callable:
        return self._function

    @property
    def function_name(self) -> str:
        return self._function_name

    @property
    def task_options(self) -> TaskOptions:
        return self._options

    def options(self, **overrides) -> "RemoteFunction":
        return RemoteFunction(self._function, build_task_options(self._options, overrides))

    def remote(self, *args, **kwargs):
        from ray_tpu._private import worker as _worker

        return _worker.global_worker().submit_task(self, args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        """DAG authoring (reference: python/ray/dag/function_node.py)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._function_name} cannot be called directly; "
            f"use .remote(...)"
        )

"""Channels: mutable shared-memory slots for compiled-graph data flow.

Reference: python/ray/experimental/channel/shared_memory_channel.py backed by
C++ mutable objects (core_worker/experimental_mutable_object_manager.cc —
versioned buffers with writer/reader synchronization). TPU-native round-1
design: a fixed-capacity /dev/shm ring slot with a seqlock header

  [u64 version][u64 payload_len][payload bytes...]

Writers bump version to odd while writing, even when done; readers spin
until they observe a new even version and a consistent snapshot. One writer,
N readers, single machine (cross-node channels ride the object plane).
"""

from __future__ import annotations

import struct
import time
from typing import Any, Optional

from ray_tpu._private.object_store import ShmSegment
from ray_tpu._private.serialization import dumps_oob, loads_oob

_HEADER = 16


class Channel:
    """Single-writer multi-reader mutable slot."""

    def __init__(self, name: str, capacity: int = 1 << 20, create: bool = False):
        self.name = f"rtpu_chan_{name}"
        self.capacity = capacity
        if create:
            self.seg = ShmSegment(self.name, capacity + _HEADER, create=True)
            struct.pack_into("<QQ", self.seg.buf, 0, 0, 0)
        else:
            self.seg = ShmSegment(self.name)
        self._last_read_version = 0

    # -- writer --

    def write(self, value: Any, timeout: Optional[float] = None):
        blob = dumps_oob(value)
        if len(blob) > self.capacity:
            raise ValueError(
                f"channel {self.name}: value of {len(blob)}B exceeds capacity "
                f"{self.capacity}B")
        version = struct.unpack_from("<Q", self.seg.buf, 0)[0]
        struct.pack_into("<Q", self.seg.buf, 0, version + 1)  # odd: writing
        self.seg.buf[_HEADER : _HEADER + len(blob)] = blob
        struct.pack_into("<Q", self.seg.buf, 8, len(blob))
        struct.pack_into("<Q", self.seg.buf, 0, version + 2)  # even: sealed

    # -- reader --

    def read(self, timeout: float = 60.0) -> Any:
        """Blocks until a version newer than the last read is available."""
        deadline = time.monotonic() + timeout
        while True:
            v1 = struct.unpack_from("<Q", self.seg.buf, 0)[0]
            if v1 % 2 == 0 and v1 > self._last_read_version:
                length = struct.unpack_from("<Q", self.seg.buf, 8)[0]
                data = bytes(self.seg.buf[_HEADER : _HEADER + length])
                v2 = struct.unpack_from("<Q", self.seg.buf, 0)[0]
                if v1 == v2:  # consistent snapshot
                    self._last_read_version = v1
                    return loads_oob(data)
            if time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name}: no new value")
            time.sleep(0.0002)

    def peek_version(self) -> int:
        return struct.unpack_from("<Q", self.seg.buf, 0)[0]

    def close(self, unlink: bool = False):
        self.seg.close()
        if unlink:
            self.seg.unlink()


class IntraProcessChannel:
    """Same-process channel (reference: intra_process_channel.py)."""

    def __init__(self):
        import queue

        self._q = queue.Queue(maxsize=1)

    def write(self, value, timeout=None):
        self._q.put(value, timeout=timeout)

    def read(self, timeout: float = 60.0):
        return self._q.get(timeout=timeout)

    def close(self, unlink: bool = False):
        pass

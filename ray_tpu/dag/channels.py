"""Channels: mutable shared-memory slots for compiled-graph data flow.

Reference: python/ray/experimental/channel/shared_memory_channel.py backed by
C++ mutable objects (core_worker/experimental_mutable_object_manager.cc —
versioned buffers with writer/reader synchronization; the writer BLOCKS
until every registered reader has consumed the previous value, so pipeline
stages observe every value, reference shared_memory_channel.py:151).

TPU-native design: a fixed-capacity /dev/shm slot with a seqlock header plus
per-reader ack slots:

  [u64 version][u64 payload_len][u32 num_readers][u32 pad]
  [u64 ack[MAX_READERS]][payload bytes...]

Writers bump version to odd while writing, even when done; readers spin
until they observe a new even version and a consistent snapshot, then ack
by storing that version in their slot. One writer, up to MAX_READERS
readers, single host (cross-host compiled graphs ride the object plane).
"""

from __future__ import annotations

import struct
import time
from typing import Any, Optional

from ray_tpu._private.object_store import ShmSegment
from ray_tpu._private.serialization import dumps_oob, loads_oob

MAX_READERS = 16
_HEADER = 24 + 8 * MAX_READERS


class ChannelClosed(Exception):
    pass


class _Stop:
    """Teardown sentinel: propagated stage to stage."""

    def __reduce__(self):
        return (_Stop, ())


STOP = _Stop()


class ChannelError:
    """Error sentinel: carries a stage's exception to downstream readers."""

    def __init__(self, err: str):
        self.err = err


class Channel:
    """Single-writer, acked multi-reader mutable slot.

    The writer passes ``num_readers`` at create time; each reader attaches
    with a distinct ``reader_slot`` in [0, num_readers). ``write`` blocks
    until all readers have acked the previous version (backpressure), so no
    reader ever misses a value.
    """

    def __init__(self, name: str, capacity: int = 1 << 20,
                 create: bool = False, num_readers: int = 1,
                 reader_slot: Optional[int] = None):
        if num_readers > MAX_READERS:
            raise ValueError(f"at most {MAX_READERS} readers per channel")
        self.name = f"rtpu_chan_{name}"
        self.capacity = capacity
        self.num_readers = num_readers
        self.reader_slot = reader_slot
        if create:
            self.seg = ShmSegment(self.name, capacity + _HEADER, create=True)
            self.seg.buf[:_HEADER] = b"\x00" * _HEADER
            struct.pack_into("<I", self.seg.buf, 16, num_readers)
        else:
            self.seg = ShmSegment(self.name)
            self.capacity = self.seg.size - _HEADER
            self.num_readers = struct.unpack_from("<I", self.seg.buf, 16)[0]
            if self.reader_slot is None:
                self.reader_slot = 0  # single-reader attach convenience
        self._last_read_version = 0

    # -- header accessors --

    def _version(self) -> int:
        return struct.unpack_from("<Q", self.seg.buf, 0)[0]

    def _ack(self, slot: int) -> int:
        return struct.unpack_from("<Q", self.seg.buf, 24 + 8 * slot)[0]

    # -- writer --

    def write(self, value: Any, timeout: Optional[float] = 300.0):
        blob = dumps_oob(value)
        if len(blob) > self.capacity:
            raise ValueError(
                f"channel {self.name}: value of {len(blob)}B exceeds capacity "
                f"{self.capacity}B")
        version = self._version()
        if version % 2 != 0:
            raise RuntimeError(f"channel {self.name}: concurrent writer")
        # backpressure: every reader must have consumed the current value
        # before it is overwritten (reader-ack; no value is ever dropped)
        if version > 0:
            deadline = time.monotonic() + (timeout or 300.0)
            spins = 0
            while any(self._ack(i) < version for i in range(self.num_readers)):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"channel {self.name}: reader did not consume value")
                spins += 1
                time.sleep(0 if spins < 2000 else 0.0002)
        struct.pack_into("<Q", self.seg.buf, 0, version + 1)  # odd: writing
        self.seg.buf[_HEADER : _HEADER + len(blob)] = blob
        struct.pack_into("<Q", self.seg.buf, 8, len(blob))
        struct.pack_into("<Q", self.seg.buf, 0, version + 2)  # even: sealed

    # -- reader --

    def read(self, timeout: float = 300.0) -> Any:
        """Blocks until a version newer than the last read is available,
        then acks it (freeing the writer to produce the next value)."""
        if self.reader_slot is None:
            raise RuntimeError("attach with reader_slot to read")
        deadline = time.monotonic() + timeout
        spins = 0
        while True:
            v1 = self._version()
            if v1 % 2 == 0 and v1 > self._last_read_version:
                length = struct.unpack_from("<Q", self.seg.buf, 8)[0]
                data = bytes(self.seg.buf[_HEADER : _HEADER + length])
                v2 = self._version()
                if v1 == v2:  # consistent snapshot
                    self._last_read_version = v1
                    value = loads_oob(data)
                    struct.pack_into("<Q", self.seg.buf, 24 + 8 * self.reader_slot, v1)
                    return value
            if time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name}: no new value")
            # adaptive: spin hot briefly (hop latency ~µs), then yield
            spins += 1
            time.sleep(0 if spins < 2000 else 0.0002)

    def peek_version(self) -> int:
        return self._version()

    def close(self, unlink: bool = False):
        self.seg.close()
        if unlink:
            self.seg.unlink()


class IntraProcessChannel:
    """Same-process channel (reference: intra_process_channel.py)."""

    def __init__(self):
        import queue

        self._q = queue.Queue(maxsize=1)

    def write(self, value, timeout=None):
        self._q.put(value, timeout=timeout)

    def read(self, timeout: float = 300.0):
        return self._q.get(timeout=timeout)

    def close(self, unlink: bool = False):
        pass


# ---------------------------------------------------------------------------
# cross-host channels (reference: the cross-node leg of compiled-graph
# channels, experimental_mutable_object_provider.cc — a writer pushes each
# version to a reader-hosted mailbox; the awaited push is the backpressure)
# ---------------------------------------------------------------------------


class CrossHostWriter:
    """Single writer pushing every value to each reader's worker mailbox
    over the worker RPC plane (out-of-band buffers ride zero-copy frames)."""

    def __init__(self, name: str, push_targets):
        from ray_tpu._private import worker as worker_mod

        self.name = name
        self._targets = list(push_targets)  # [(mailbox_name, worker_addr)]
        self._w = worker_mod.global_worker()

    def write(self, value: Any, timeout: Optional[float] = 300.0):
        import asyncio
        from ray_tpu._private import wire as _p

        blob = dumps_oob(value)
        t = timeout or 300.0
        # concurrent fan-out: one slow reader only costs its own mailbox
        # push, not a serial wait in front of every later reader (the
        # bounded mailbox still backpressures the writer per-reader)
        calls = [self._w._worker_client(addr).call(
            "ChanPush", _p.dumps({"name": mbox, "blob": blob}),
            timeout=t, retries=0) for mbox, addr in self._targets]

        async def _fanout():
            await asyncio.gather(*calls)

        self._w._run(_fanout(), t + 10.0)

    def read(self, timeout: float = 300.0):
        raise RuntimeError("cross-host channel writer cannot read")

    def close(self, unlink: bool = False):
        pass


class CrossHostReader:
    """Reader end: pops from THIS worker's mailbox (values were pushed by
    the remote writer)."""

    def __init__(self, mailbox: str):
        from ray_tpu._private import worker as worker_mod

        self.name = mailbox
        self._w = worker_mod.global_worker()

    def read(self, timeout: float = 300.0) -> Any:
        return loads_oob(self._w.chan_pop(self.name, timeout))

    def write(self, value, timeout=None):
        raise RuntimeError("cross-host channel reader cannot write")

    def close(self, unlink: bool = False):
        if unlink:
            self._w.chan_close(self.name)


def open_reader(name: str, slot: int, spec: Optional[dict] = None):
    """Channel factory, reader side: shm seqlock slot (same-node) or the
    per-reader cross-host mailbox."""
    if spec and spec.get("type") == "xhost":
        return CrossHostReader(f"{name}@{slot}")
    return Channel(name, reader_slot=slot)


def open_writer(name: str, spec: Optional[dict] = None):
    if spec and spec.get("type") == "xhost":
        return CrossHostWriter(name, spec["push"])
    return Channel(name)

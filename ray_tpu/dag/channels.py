"""Channels: mutable shared-memory slot rings for compiled-graph and
pipeline data flow.

Reference: python/ray/experimental/channel/shared_memory_channel.py backed by
C++ mutable objects (core_worker/experimental_mutable_object_manager.cc —
versioned buffers with writer/reader synchronization; the writer BLOCKS
until every registered reader has consumed the value ``depth`` writes back,
so pipeline stages observe every value, reference
shared_memory_channel.py:151).

TPU-native design: a fixed-capacity /dev/shm segment holding a ring of
``depth`` seqlock slots. Global write sequence ``n`` lands in slot
``n % depth`` and seals it at version ``2*(n//depth) + 2`` (odd while
writing). Each slot carries per-reader ack words; the writer of value ``n``
first waits until every reader has acked value ``n - depth`` (the previous
occupant of the slot), which keeps the no-drop rendezvous while letting the
producer run ``depth`` values ahead — with ``depth >= 2`` a pipeline stage's
SEND overlaps its next compute op instead of blocking on the downstream ack.

Segment layout (all offsets 64-byte aligned)::

  [u32 magic][u32 depth][u32 num_readers][u32 _][u64 slot_capacity] pad->64
  depth x slots:
    [u64 version][u64 payload_len][u64 seq][u64 ack[MAX_READERS]] pad->192
    [payload bytes ... slot_capacity]

Payloads use array-aware zero-copy framing: pytree leaves that are numpy /
jax arrays are copied straight into the slot (one memcpy, no pickle), and a
small pickled *skeleton* — the tree with leaves replaced by placeholders,
plus per-leaf (dtype, shape, quantization) metadata — rides alongside a
buffer table::

  [u8 fmt][u8 _ x3][u32 skel_len][u32 nbufs][u32 _]
  [ (u64 off, u64 nbytes) x nbufs ]  [skel pickle]  pad->64  [buffers...]

The reader validates ``payload_len`` and ``seq`` *under* the version
snapshot (a torn header can otherwise present a garbage length), copies the
raw payload, re-checks the version, and acks BEFORE deserializing — writer
backpressure releases at copy time, not at unpickle time. Arrays
materialize as views over the private copy (no intermediate ``bytes()``).

One writer, up to MAX_READERS readers, single host (cross-host compiled
graphs ride the object plane).
"""

from __future__ import annotations

import struct
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from ray_tpu._private.object_store import ShmSegment
from ray_tpu._private.serialization import dumps_oob, loads_oob

MAX_READERS = 16
_SEG_HDR = 64
_SLOT_HDR = 192  # u64 version + u64 len + u64 seq + u64 ack[16] = 152 -> 192
_MAGIC = 0x52544332  # "RTC2"
_ALIGN = 64
_PAYLOAD_HDR = 16  # u8 fmt + pad + u32 skel_len + u32 nbufs + pad
_FMT_TREE = 1
_XHOST_RETRIES = 3


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class ChannelClosed(Exception):
    pass


class _Stop:
    """Teardown sentinel: propagated stage to stage."""

    def __reduce__(self):
        return (_Stop, ())


STOP = _Stop()


class ChannelError:
    """Error sentinel: carries a stage's exception to downstream readers."""

    def __init__(self, err: str):
        self.err = err


class _Leaf:
    """Skeleton placeholder for an array leaf (index into the leaf table)."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i

    def __reduce__(self):
        return (_Leaf, (self.i,))


def _is_array_leaf(x: Any) -> bool:
    if isinstance(x, np.ndarray):
        return not x.dtype.hasobject
    mod = type(x).__module__
    return ((mod.startswith("jax") or mod.startswith("jaxlib"))
            and hasattr(x, "__array__") and hasattr(x, "dtype"))


def _extract_leaves(value: Any) -> Tuple[Any, List[np.ndarray]]:
    """Replace array leaves of dict/list/tuple containers with placeholders;
    anything else stays inline in the skeleton pickle."""
    leaves: List[np.ndarray] = []

    def walk(x):
        if _is_array_leaf(x):
            a = np.asarray(x)
            if not a.flags["C_CONTIGUOUS"]:  # ascontiguousarray would
                a = np.ascontiguousarray(a)  # promote 0-d to shape (1,)
            leaves.append(a)
            return _Leaf(len(leaves) - 1)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        if isinstance(x, tuple):
            items = [walk(v) for v in x]
            return type(x)(*items) if hasattr(x, "_fields") else tuple(items)
        if isinstance(x, list):
            return [walk(v) for v in x]
        return x

    return walk(value), leaves


def _plant_leaves(skel: Any, leaves: List[np.ndarray]) -> Any:
    def walk(x):
        if isinstance(x, _Leaf):
            return leaves[x.i]
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        if isinstance(x, tuple):
            items = [walk(v) for v in x]
            return type(x)(*items) if hasattr(x, "_fields") else tuple(items)
        if isinstance(x, list):
            return [walk(v) for v in x]
        return x

    return walk(skel)


def _encode_frame(value: Any, codec=None) -> Tuple[bytes, list, dict]:
    """Build the frame: returns (skeleton blob, buffer list, stats).

    With a codec, float leaves stream quantized (codes + fp32 block scales
    as separate buffers); non-float leaves always take the exact path.
    """
    t0 = time.perf_counter()
    skel, leaves = _extract_leaves(value)
    metas = []
    bufs: List[np.ndarray] = []
    for leaf in leaves:
        if codec is not None and np.issubdtype(leaf.dtype, np.floating):
            from ray_tpu.collective.quant import quantize

            qt = quantize(leaf, codec)
            m = {"bi": len(bufs), "q": qt.meta(), "sbi": None}
            bufs.append(qt.codes)
            if qt.scales.size:
                m["sbi"] = len(bufs)
                bufs.append(qt.scales.view(np.uint8))
            metas.append(m)
        else:
            metas.append({"bi": len(bufs), "dtype": leaf.dtype,
                          "shape": leaf.shape})
            # reshape first: 0-d arrays reject dtype-changing views
            bufs.append(leaf.reshape(-1).view(np.uint8))
    t1 = time.perf_counter()
    skel_blob = dumps_oob((skel, metas))
    t2 = time.perf_counter()
    return skel_blob, bufs, {"encode_s": t1 - t0, "pickle_s": t2 - t1,
                             "skel_bytes": len(skel_blob)}


def _decode_frame(raw: np.ndarray) -> Any:
    """Rebuild the value from a private copy of the payload (post-ack)."""
    fmt = int(raw[0])
    if fmt != _FMT_TREE:
        raise RuntimeError(f"unknown channel frame format {fmt}")
    skel_len, nbufs = struct.unpack_from("<II", raw, 4)
    table_end = _PAYLOAD_HDR + 16 * nbufs
    table = np.frombuffer(raw, "<u8", count=2 * nbufs,
                          offset=_PAYLOAD_HDR).reshape(nbufs, 2)
    skel, metas = loads_oob(raw[table_end:table_end + skel_len].tobytes())
    leaves = []
    for m in metas:
        off, nb = int(table[m["bi"], 0]), int(table[m["bi"], 1])
        b = raw[off:off + nb]
        if "q" in m:
            from ray_tpu.collective.quant import QuantizedTensor, dequantize

            q = m["q"]
            if m["sbi"] is not None:
                soff, snb = (int(table[m["sbi"], 0]),
                             int(table[m["sbi"], 1]))
                scales = raw[soff:soff + snb].view(np.float32)
            else:
                scales = np.zeros(0, np.float32)
            leaves.append(dequantize(QuantizedTensor(
                q["codec"], q["block"], tuple(q["shape"]), q["dtype"],
                b, scales)))
        else:
            leaves.append(b.view(m["dtype"]).reshape(m["shape"]))
    return _plant_leaves(skel, leaves)


class Channel:
    """Single-writer, acked multi-reader mutable slot ring.

    The writer passes ``num_readers`` and ``depth`` at create time; each
    reader attaches with a distinct ``reader_slot`` in [0, num_readers).
    ``write`` of value ``n`` blocks until all readers have acked value
    ``n - depth`` (ring backpressure), so no reader ever misses a value.
    Attach-side endpoints derive their resume sequence from the shm state
    (slot seqs for writers, own ack words for readers), so a restarted
    process re-joins an in-flight ring where it left off.
    """

    def __init__(self, name: str, capacity: int = 1 << 20,
                 create: bool = False, num_readers: int = 1,
                 reader_slot: Optional[int] = None, depth: int = 1):
        if num_readers > MAX_READERS:
            raise ValueError(f"at most {MAX_READERS} readers per channel")
        if depth < 1:
            raise ValueError(f"channel depth must be >= 1, got {depth}")
        self.name = f"rtpu_chan_{name}"
        self.capacity = _align(capacity)
        self.num_readers = num_readers
        self.reader_slot = reader_slot
        self.depth = depth
        self._codec = None
        self.last_write_stats: dict = {}
        self.last_read_stats: dict = {}
        stride = _SLOT_HDR + self.capacity
        if create:
            size = _SEG_HDR + depth * stride
            self.seg = ShmSegment(self.name, size, create=True)
            self.seg.buf[:size] = b"\x00" * size
            struct.pack_into("<IIII Q", self.seg.buf, 0, _MAGIC, depth,
                             num_readers, 0, self.capacity)
            self._wseq = 0
            self._rseq = 0
        else:
            self.seg = ShmSegment(self.name)
            magic, depth, nr, _, cap = struct.unpack_from(
                "<IIII Q", self.seg.buf, 0)
            if magic != _MAGIC:
                raise RuntimeError(
                    f"channel {self.name}: bad segment magic {magic:#x}")
            self.depth, self.num_readers, self.capacity = depth, nr, int(cap)
            if self.reader_slot is None:
                self.reader_slot = 0  # single-reader attach convenience
            # resume sequences from shm state (crash-restart safe)
            best = -1
            for i in range(self.depth):
                v = self._version(i)
                if v and v % 2 == 0:
                    best = max(best, (v // 2 - 1) * self.depth + i)
            self._wseq = best + 1
            best = -1
            for i in range(self.depth):
                a = self._ack(i, self.reader_slot)
                if a:
                    best = max(best, (a // 2 - 1) * self.depth + i)
            self._rseq = best + 1

    def set_codec(self, codec) -> None:
        """Quantized streaming for float leaves of subsequent writes
        (None / "int8" / "fp8" / "bf16" / QuantCodec)."""
        from ray_tpu.collective.quant import resolve_codec

        self._codec = resolve_codec(codec)

    # -- slot accessors --

    def _slot_base(self, slot: int) -> int:
        return _SEG_HDR + slot * (_SLOT_HDR + self.capacity)

    def _version(self, slot: int) -> int:
        return struct.unpack_from("<Q", self.seg.buf, self._slot_base(slot))[0]

    def _length(self, slot: int) -> int:
        return struct.unpack_from(
            "<Q", self.seg.buf, self._slot_base(slot) + 8)[0]

    def _seq(self, slot: int) -> int:
        return struct.unpack_from(
            "<Q", self.seg.buf, self._slot_base(slot) + 16)[0]

    def _ack(self, slot: int, reader: int) -> int:
        return struct.unpack_from(
            "<Q", self.seg.buf, self._slot_base(slot) + 24 + 8 * reader)[0]

    def _acks(self, slot: int) -> List[int]:
        return [self._ack(slot, i) for i in range(self.num_readers)]

    # -- writer --

    def write(self, value: Any, timeout: Optional[float] = 300.0):
        skel_blob, bufs, stats = _encode_frame(value, self._codec)
        nbufs = len(bufs)
        table_off = _PAYLOAD_HDR
        skel_off = table_off + 16 * nbufs
        offs = []
        cursor = _align(skel_off + len(skel_blob))
        for b in bufs:
            offs.append(cursor)
            cursor = _align(cursor + b.nbytes)
        total = cursor
        if total > self.capacity:
            raise ValueError(
                f"channel {self.name}: value of {total}B exceeds slot "
                f"capacity {self.capacity}B")
        n = self._wseq
        slot = n % self.depth
        base = self._slot_base(slot)
        sealed = 2 * (n // self.depth) + 2
        version = self._version(slot)
        if version % 2 != 0:
            raise RuntimeError(f"channel {self.name}: concurrent writer")
        # ring backpressure: every reader must have consumed the value that
        # previously occupied this slot (seq n - depth) before overwrite
        t0 = time.perf_counter()
        if n >= self.depth:
            deadline = time.monotonic() + (timeout or 300.0)
            spins = 0
            while any(a < sealed - 2 for a in self._acks(slot)):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"channel {self.name}: reader did not consume value "
                        f"seq {n - self.depth} (slot {slot} version "
                        f"{self._version(slot)}, acks={self._acks(slot)}, "
                        f"want ack >= {sealed - 2})")
                spins += 1
                time.sleep(0 if spins < 2000 else 0.0002)
        stats["ack_wait_s"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        struct.pack_into("<Q", self.seg.buf, base, sealed - 1)  # odd: writing
        pbase = base + _SLOT_HDR
        struct.pack_into("<BxxxIIxxxx", self.seg.buf, pbase, _FMT_TREE,
                         len(skel_blob), nbufs)
        for i, b in enumerate(bufs):
            struct.pack_into("<QQ", self.seg.buf, pbase + table_off + 16 * i,
                             offs[i], b.nbytes)
        self.seg.buf[pbase + skel_off:pbase + skel_off + len(skel_blob)] = \
            skel_blob
        dst = np.frombuffer(self.seg.buf, np.uint8, count=self.capacity,
                            offset=pbase)
        for b, off in zip(bufs, offs):
            if b.nbytes:
                dst[off:off + b.nbytes] = b.reshape(-1).view(np.uint8)
        struct.pack_into("<QQ", self.seg.buf, base + 8, total, n)
        struct.pack_into("<Q", self.seg.buf, base, sealed)  # even: sealed
        stats["copy_s"] = time.perf_counter() - t0
        stats["wire_bytes"] = total
        self._wseq = n + 1
        self.last_write_stats = stats

    # -- reader --

    def read(self, timeout: float = 300.0) -> Any:
        """Blocks until value ``n`` (this reader's next sequence) is sealed
        in its ring slot, copies it under a consistent version snapshot,
        acks (freeing the writer), THEN deserializes."""
        if self.reader_slot is None:
            raise RuntimeError("attach with reader_slot to read")
        n = self._rseq
        slot = n % self.depth
        base = self._slot_base(slot)
        want = 2 * (n // self.depth) + 2
        deadline = time.monotonic() + timeout
        spins = 0
        t_start = time.perf_counter()
        while True:
            v1 = self._version(slot)
            if v1 == want:
                # length and seq validated UNDER the snapshot: a torn header
                # mid-write must never drive the payload copy
                length = self._length(slot)
                if _PAYLOAD_HDR <= length <= self.capacity \
                        and self._seq(slot) == n:
                    t0 = time.perf_counter()
                    raw = np.empty(length, np.uint8)
                    raw[:] = np.frombuffer(self.seg.buf, np.uint8,
                                           count=length,
                                           offset=base + _SLOT_HDR)
                    if self._version(slot) == v1:  # consistent snapshot
                        t1 = time.perf_counter()
                        # ack BEFORE deserializing: writer backpressure
                        # releases at copy time, decode overlaps the next
                        # upstream write
                        struct.pack_into(
                            "<Q", self.seg.buf,
                            base + 24 + 8 * self.reader_slot, want)
                        self._rseq = n + 1
                        value = _decode_frame(raw)
                        t2 = time.perf_counter()
                        self.last_read_stats = {
                            "wait_s": t0 - t_start, "copy_s": t1 - t0,
                            "decode_s": t2 - t1, "wire_bytes": int(length)}
                        return value
            elif v1 > want:
                raise RuntimeError(
                    f"channel {self.name}: reader {self.reader_slot} lost "
                    f"sync at seq {n} (slot {slot} version {v1} > expected "
                    f"{want}; writer overwrote an unacked value)")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel {self.name}: no value for seq {n} after "
                    f"{timeout}s (slot {slot}: version={self._version(slot)} "
                    f"want={want} len={self._length(slot)} "
                    f"slot_seq={self._seq(slot)} acks={self._acks(slot)})")
            # adaptive: spin hot briefly (hop latency ~µs), then yield
            spins += 1
            time.sleep(0 if spins < 2000 else 0.0002)

    def peek_version(self) -> int:
        return self._version((self._rseq if self.reader_slot is not None
                              else self._wseq) % self.depth)

    def close(self, unlink: bool = False):
        self.seg.close()
        if unlink:
            self.seg.unlink()


class IntraProcessChannel:
    """Same-process channel (reference: intra_process_channel.py)."""

    def __init__(self, depth: int = 1):
        import queue

        self._q = queue.Queue(maxsize=depth)

    def write(self, value, timeout=None):
        self._q.put(value, timeout=timeout)

    def read(self, timeout: float = 300.0):
        return self._q.get(timeout=timeout)

    def close(self, unlink: bool = False):
        pass


# ---------------------------------------------------------------------------
# cross-host channels (reference: the cross-node leg of compiled-graph
# channels, experimental_mutable_object_provider.cc — a writer pushes each
# version to a reader-hosted mailbox; the awaited push is the backpressure)
# ---------------------------------------------------------------------------


class CrossHostWriter:
    """Single writer pushing every value to each reader's worker mailbox
    over the worker RPC plane (out-of-band buffers ride zero-copy frames).

    Pushes carry a per-channel sequence number and retry transient RPC
    failures with backoff; the mailbox dedups on the sequence so a retried
    push after an ambiguous failure never double-delivers."""

    def __init__(self, name: str, push_targets):
        from ray_tpu._private import worker as worker_mod

        self.name = name
        self._targets = list(push_targets)  # [(mailbox_name, worker_addr)]
        self._w = worker_mod.global_worker()
        self._seq = 0

    def write(self, value: Any, timeout: Optional[float] = 300.0):
        import asyncio
        from ray_tpu._private import wire as _p

        blob = dumps_oob(value)
        seq = self._seq
        self._seq += 1
        t = timeout or 300.0

        # concurrent fan-out: one slow reader only costs its own mailbox
        # push, not a serial wait in front of every later reader (the
        # bounded mailbox still backpressures the writer per-reader)
        async def _push(mbox, addr):
            msg = _p.dumps({"name": mbox, "blob": blob, "seq": seq})
            delay = 0.05
            for attempt in range(_XHOST_RETRIES + 1):
                try:
                    await self._w._worker_client(addr).call(
                        "ChanPush", msg, timeout=t, retries=0)
                    return
                except asyncio.CancelledError:
                    raise
                except Exception:  # transient RPC surface; idempotent via seq
                    if attempt == _XHOST_RETRIES:
                        raise
                    await asyncio.sleep(delay)
                    delay *= 2

        async def _fanout():
            await asyncio.gather(*[_push(m, a) for m, a in self._targets])

        self._w._run(_fanout(), t * (_XHOST_RETRIES + 1) + 10.0)

    def read(self, timeout: float = 300.0):
        raise RuntimeError("cross-host channel writer cannot read")

    def close(self, unlink: bool = False):
        pass


class CrossHostReader:
    """Reader end: pops from THIS worker's mailbox (values were pushed by
    the remote writer)."""

    def __init__(self, mailbox: str):
        from ray_tpu._private import worker as worker_mod

        self.name = mailbox
        self._w = worker_mod.global_worker()

    def read(self, timeout: float = 300.0) -> Any:
        return loads_oob(self._w.chan_pop(self.name, timeout))

    def write(self, value, timeout=None):
        raise RuntimeError("cross-host channel reader cannot write")

    def close(self, unlink: bool = False):
        if unlink:
            self._w.chan_close(self.name)


def open_reader(name: str, slot: int, spec: Optional[dict] = None):
    """Channel factory, reader side: shm seqlock ring (same-node) or the
    per-reader cross-host mailbox."""
    if spec and spec.get("type") == "xhost":
        return CrossHostReader(f"{name}@{slot}")
    return Channel(name, reader_slot=slot)


def open_writer(name: str, spec: Optional[dict] = None):
    if spec and spec.get("type") == "xhost":
        return CrossHostWriter(name, spec["push"])
    return Channel(name)

"""Compiled-graph actor-side executor.

Reference: python/ray/dag/dag_node_operation.py:704 — compilation emits a
STATIC per-actor schedule (ordered read/compute/write ops); each actor runs
its schedule in a loop over the channel data plane with no per-iteration
control-plane traffic. The driver only writes the input channel and reads
the output channel.

The schedule shipped to an actor:
  {"chan_readers": {chan_name: reader_slot},   # one slot per (actor, chan)
   "ops": [
     {"method": str,                 # method name on the actor instance
      "args": [("const", value) |   # literal argument
               ("chan", name) |     # this iteration's value of a channel
               ("chan_idx", (name, i)) |  # ...indexed (InputNode slots)
               ("local", op_index)],      # output of an earlier op here
      "out": Optional[str]}]}       # channel to write the result to

Every channel is read at most once per iteration per actor (values fan out
to all ops through the iteration cache), and every out-channel receives
exactly one value (result, error, or stop) per iteration — so downstream
readers observe every iteration in order.
"""

from __future__ import annotations

import logging
import threading
import traceback
from typing import Any, Dict, List, Optional

from ray_tpu.dag.channels import Channel, ChannelError, _Stop

logger = logging.getLogger("ray_tpu.dag")

DAG_LOOP_METHOD = "__rtpu_dag_loop__"


class DagLoopRunner:
    """Runs one actor's static schedule until a STOP sentinel arrives."""

    def __init__(self, instance: Any, schedule: dict):
        from ray_tpu.dag.channels import open_reader, open_writer

        self.instance = instance
        self.ops: List[dict] = schedule["ops"]
        specs = schedule.get("chan_specs") or {}
        self._read_chans: Dict[str, Any] = {}
        self._write_chans: Dict[str, Any] = {}
        for name, slot in (schedule.get("chan_readers") or {}).items():
            self._read_chans[name] = open_reader(name, slot, specs.get(name))
        for op in self.ops:
            if op.get("out"):
                self._write_chans[op["out"]] = open_writer(
                    op["out"], specs.get(op["out"]))
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="rtpu-dag-loop", daemon=True)
        self._thread.start()

    def _run(self):
        try:
            while self._run_one_iteration():
                pass
        except Exception:
            logger.exception("dag loop crashed")

    def _run_one_iteration(self) -> bool:
        chan_cache: Dict[str, Any] = {}
        locals_: Dict[int, Any] = {}
        saw_stop = False

        def chan_value(name):
            if name not in chan_cache:
                chan_cache[name] = self._read_chans[name].read()
            return chan_cache[name]

        for idx, op in enumerate(self.ops):
            args = []
            sentinel = None  # _Stop or ChannelError poisoning this op
            for kind, v in op["args"]:
                if kind == "const":
                    value = v
                elif kind == "chan":
                    value = chan_value(v)
                elif kind == "chan_idx":
                    value = chan_value(v[0])
                    if not isinstance(value, (_Stop, ChannelError)):
                        value = value[v[1]]
                elif kind == "local_ici":
                    # compiled ICI edge: move the upstream op's sharded
                    # output to this stage's mesh position via the cached
                    # jitted ppermute (reference: accelerator channels)
                    value = locals_[v[0]]
                    if not isinstance(value, (_Stop, ChannelError)):
                        from ray_tpu.dag.device_channel import get_transfer

                        value = get_transfer(self.instance, v[1])(value)
                else:  # local
                    value = locals_[v]
                if isinstance(value, _Stop):
                    sentinel = value  # teardown wins over error propagation
                    saw_stop = True
                elif isinstance(value, ChannelError):
                    sentinel = sentinel or value
                args.append(value)
            if sentinel is not None:
                result = sentinel
            else:
                try:
                    result = getattr(self.instance, op["method"])(*args)
                except Exception as e:
                    result = ChannelError(
                        f"{type(e).__name__}: {e}\n{traceback.format_exc()}")
            locals_[idx] = result
            if op.get("out"):
                self._write_chans[op["out"]].write(result)
        return not saw_stop

"""Compiled ICI edge tier for compiled graphs.

Reference: python/ray/experimental/channel/torch_tensor_accelerator_channel.py
— the reference moves GPU tensors between pipeline stages over NCCL
send/recv instead of the host channel plane. The TPU-native equivalent: an
edge annotated ``.with_tensor_transport("ici")`` lowers to ONE jitted
``shard_map`` ``lax.ppermute`` step over the stage actor's device mesh — the
microbatch hand-off rides the ICI interconnect inside the compiled program;
no serialization, no shm slot, no RPC. On a multi-host slice the same
program lowers to inter-chip collectives under multi-controller SPMD (the
Train worker-group bootstrap); in CI it runs on the virtual 8-device CPU
mesh.
"""

from __future__ import annotations

from typing import Optional

_COMPILE_COUNTS: dict = {}  # transfer key -> times the jit was BUILT (tests)
_CALL_COUNTS: dict = {}  # transfer key -> times the compiled step ran


class IciTransfer:
    """One compiled mesh-shift step: shard i's value moves to shard
    (i + shift) % world. Built once per (mesh, shift); every call after the
    first reuses the compiled executable."""

    def __init__(self, mesh=None, shift: int = 1, axis: str = "ici"):
        from ray_tpu.utils import import_jax

        jax = import_jax()
        if mesh is None:
            import numpy as np
            from jax.sharding import Mesh

            mesh = Mesh(np.array(jax.devices()), (axis,))
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.shift = shift
        n = mesh.devices.size
        perm = [(i, (i + shift) % n) for i in range(n)]
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        spec = P(self.axis)
        axis = self.axis

        def _step(x):
            from jax import lax

            return lax.ppermute(x, axis, perm)

        self._fn = jax.jit(shard_map(
            _step, mesh=mesh, in_specs=spec, out_specs=spec, check_rep=False))
        self.key = (id(mesh), shift)
        _COMPILE_COUNTS[self.key] = _COMPILE_COUNTS.get(self.key, 0) + 1

    def __call__(self, x):
        _CALL_COUNTS[self.key] = _CALL_COUNTS.get(self.key, 0) + 1
        from ray_tpu.utils import import_jax

        jax = import_jax()
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        if not isinstance(x, jax.Array):
            x = jax.device_put(
                x, NamedSharding(self.mesh, P(self.axis)))
        return self._fn(x)


def get_transfer(instance, shift: int = 1) -> IciTransfer:
    """Per-actor cached transfer; the mesh comes from the actor's ``mesh``
    attribute (the slice mesh a stage actor already owns) or defaults to a
    1-D mesh over all visible devices."""
    cache = getattr(instance, "__rtpu_ici_transfers__", None)
    if cache is None:
        cache = {}
        try:
            instance.__rtpu_ici_transfers__ = cache
        except AttributeError:  # raylint: disable=EXC001 slots-only actor class; fall back to uncached transfers
            pass
    t = cache.get(shift)
    if t is None:
        t = IciTransfer(mesh=getattr(instance, "mesh", None), shift=shift)
        cache[shift] = t
    return t


def transfer_stats() -> dict:
    return {"compiles": dict(_COMPILE_COUNTS), "calls": dict(_CALL_COUNTS)}

"""DAG authoring + compiled execution (reference: python/ray/dag).

``fn.bind(...)`` / ``Actor.bind(...)`` / ``handle.method.bind(...)`` build a
lazy graph (dag_node.py, class_node.py, input_node.py); ``execute`` walks it;
``experimental_compile`` (dag_node.py:279) returns a ``CompiledDAG`` with a
precomputed topological schedule.

Scope note: the compiled path pre-resolves the schedule and reuses
pickled task payloads, but still rides the normal actor-call RPC plane;
the shared-memory mutable-object channel data plane (reference:
experimental/channel/shared_memory_channel.py + the seqlock C++ side)
lives in channels.py — seqlock slot RINGS (depth >= 2) with per-reader
acks, zero-copy array framing (tree-skeleton header, leaf buffers
memcpy'd into the slot, no pickle on the hot path), optional quantized
activation streaming, and a seq-deduped cross-host mailbox writer. The
pipeline plane (ray_tpu/train/pipeline) is its primary consumer; wiring
the compiled DAG executor itself over these channels is the remaining
tier of this module.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.object_ref import ObjectRef


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._transport: Optional[dict] = None

    def with_tensor_transport(self, transport: str = "ici", *,
                              shift: int = 1) -> "DAGNode":
        """Annotate this node's OUTGOING edges (reference:
        DAGNode.with_tensor_transport / with_type_hint). transport="ici"
        lowers same-actor edges to a compiled shard_map ppermute over the
        actor's mesh (dag/device_channel.py) — the hand-off rides ICI
        inside the compiled program instead of the host channel plane.
        Cross-actor edges fall back to the channel plane (multi-controller
        slice actors execute the same compiled step on device instead)."""
        if transport not in ("ici", "object"):
            raise ValueError(f"unknown tensor transport {transport!r}")
        self._transport = None if transport == "object" else {
            "type": transport, "shift": shift}
        return self

    def _deps(self) -> List["DAGNode"]:
        out = []
        for v in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(v, DAGNode):
                out.append(v)
        return out

    def execute(self, *input_args, **input_kwargs):
        """Eagerly execute the graph rooted here; returns an ObjectRef."""
        cache: Dict[int, Any] = {}
        return self._execute_node(input_args, input_kwargs, cache)

    def _resolve(self, v, input_args, input_kwargs, cache):
        if isinstance(v, DAGNode):
            return v._execute_node(input_args, input_kwargs, cache)
        return v

    def _resolved_args(self, input_args, input_kwargs, cache):
        args = [self._resolve(a, input_args, input_kwargs, cache)
                for a in self._bound_args]
        kwargs = {k: self._resolve(v, input_args, input_kwargs, cache)
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_node(self, input_args, input_kwargs, cache):
        raise NotImplementedError

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)


class InputNode(DAGNode):
    """Placeholder for the value passed to execute() (reference:
    dag/input_node.py). Supports `with InputNode() as inp:` authoring."""

    def __init__(self, index: int = 0):
        super().__init__((), {})
        self._index = index

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_node(self, input_args, input_kwargs, cache):
        return input_args[self._index]


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_node(self, input_args, input_kwargs, cache):
        key = id(self)
        if key not in cache:
            args, kwargs = self._resolved_args(input_args, input_kwargs, cache)
            cache[key] = self._remote_fn.remote(*args, **kwargs)
        return cache[key]


class ClassNode(DAGNode):
    """Actor construction in a DAG; instantiated once per compiled graph."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._actor_handle = None

    def _get_actor(self, input_args, input_kwargs, cache):
        if self._actor_handle is None:
            args, kwargs = self._resolved_args(input_args, input_kwargs, cache)
            args = [ray_tpu.get(a) if isinstance(a, ObjectRef) else a for a in args]
            self._actor_handle = self._actor_cls.remote(*args, **kwargs)
        return self._actor_handle

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)

    def _execute_node(self, input_args, input_kwargs, cache):
        return self._get_actor(input_args, input_kwargs, cache)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, actor_or_node, method_name, args, kwargs):
        super().__init__(args, kwargs)
        self._target = actor_or_node
        self._method_name = method_name

    def _execute_node(self, input_args, input_kwargs, cache):
        key = id(self)
        if key not in cache:
            if isinstance(self._target, ClassNode):
                handle = self._target._get_actor(input_args, input_kwargs, cache)
            else:
                handle = self._target
            args, kwargs = self._resolved_args(input_args, input_kwargs, cache)
            method = getattr(handle, self._method_name)
            cache[key] = method.remote(*args, **kwargs)
        return cache[key]


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _execute_node(self, input_args, input_kwargs, cache):
        return [self._resolve(o, input_args, input_kwargs, cache)
                for o in self._bound_args]


class CompiledDAGRef:
    """Handle to one in-flight compiled-graph execution (reference:
    CompiledDAGRef — results must be consumed in submission order)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._consumed = False

    def get(self, timeout: float = 300.0):
        if self._consumed:
            raise ValueError("compiled DAG result was already consumed; "
                             "results can be read once, in submission order")
        self._consumed = True
        return self._dag._get_result(self._seq, timeout)


class CompiledDAG:
    """A real compiled execution plan (reference: compiled_dag_node.py:805,
    static per-actor schedules from dag_node_operation.py:704).

    Compilation assigns every ClassMethodNode to its actor, allocates one
    shared-memory channel per cross-actor edge (acked single-writer slots,
    channels.py), and ships each actor ONE static schedule which it runs on
    a dedicated thread. After compile, an execute() is a single channel
    write and get() a single channel read — the driver and the control
    plane are out of the per-iteration loop entirely.

    Single-host scope: channels live in /dev/shm (the multi-node test
    harness shares one host); cross-host edges would ride the object plane.
    """

    def __init__(self, root: DAGNode):
        import uuid as _uuid

        self._root = root
        self._order = self._toposort(root)
        self._uuid = _uuid.uuid4().hex[:10]
        self._seq = 0
        self._results_read = 0
        self._buffer: Dict[int, Any] = {}
        self._torn_down = False
        for node in self._order:
            if isinstance(node, FunctionNode):
                raise ValueError(
                    "compiled graphs support actor methods only (bind "
                    "functions run eagerly via dag.execute())")
        # instantiate all actors up front (class nodes hang off the method
        # nodes' targets, not the arg-dependency edges)
        for node in self._order:
            if isinstance(node, ClassMethodNode) \
                    and isinstance(node._target, ClassNode):
                node._target._get_actor((), {}, {})
        self._build_plan()
        self._launch_loops()

    @staticmethod
    def _toposort(root) -> List[DAGNode]:
        seen: List[DAGNode] = []
        visiting = set()

        def visit(n: DAGNode):
            if id(n) in visiting:
                raise ValueError("cycle in DAG")
            if n in seen:
                return
            visiting.add(id(n))
            for d in n._deps():
                visit(d)
            visiting.discard(id(n))
            seen.append(n)

        visit(root)
        return seen

    # -- compilation --

    def _actor_of(self, node: "ClassMethodNode"):
        if isinstance(node._target, ClassNode):
            return node._target._actor_handle
        return node._target  # pre-existing ActorHandle

    def _build_plan(self):
        """Assign ops to actors, allocate channels, build schedules."""
        from ray_tpu.dag.channels import Channel

        method_nodes = [n for n in self._order
                        if isinstance(n, ClassMethodNode)]
        self._input_chan_name = f"{self._uuid}_in"
        # node -> producing channel name (cross-actor edges only)
        chan_of: Dict[int, str] = {}
        terminals: List[DAGNode] = (
            list(self._root._bound_args)
            if isinstance(self._root, MultiOutputNode) else [self._root])
        self._num_outputs = len(terminals)
        for i, t in enumerate(terminals):
            if not isinstance(t, ClassMethodNode):
                raise ValueError("compiled DAG outputs must be actor methods")
        # channels: input + one per method node that has any cross-actor or
        # driver reader
        readers_of: Dict[str, List[Any]] = {self._input_chan_name: []}
        for n in method_nodes:
            chan_of[id(n)] = f"{self._uuid}_{len(chan_of)}"
            readers_of[chan_of[id(n)]] = []

        def note_reader(chan_name, party):
            lst = readers_of[chan_name]
            if all(p is not party for p in lst):
                lst.append(party)

        # who reads what
        schedules: Dict[Any, dict] = {}  # actor handle -> schedule

        def sched_for(actor):
            key = actor.actor_id
            if key not in schedules:
                schedules[key] = {"actor": actor, "chan_readers": {},
                                  "ops": [], "node_idx": {}}
            return schedules[key]

        for n in method_nodes:
            actor = self._actor_of(n)
            sched = sched_for(actor)
            arg_spec = []
            for v in list(n._bound_args) + list(n._bound_kwargs.values()):
                if isinstance(v, InputNode):
                    note_reader(self._input_chan_name, sched)
                    arg_spec.append(("chan_idx",
                                     (self._input_chan_name, v._index)))
                elif isinstance(v, ClassMethodNode):
                    if self._actor_of(v) == actor:
                        tp = getattr(v, "_transport", None)
                        if tp and tp.get("type") == "ici":
                            # compiled ICI hop: the producer's sharded
                            # output shifts one mesh position inside a
                            # jitted ppermute (device_channel.IciTransfer)
                            arg_spec.append(("local_ici", (
                                sched["node_idx"][id(v)], tp.get("shift", 1))))
                        else:
                            arg_spec.append(
                                ("local", sched["node_idx"][id(v)]))
                    else:
                        cname = chan_of[id(v)]
                        note_reader(cname, sched)
                        arg_spec.append(("chan", cname))
                elif isinstance(v, DAGNode):
                    raise ValueError(
                        f"unsupported node type in compiled DAG: {type(v)}")
                else:
                    arg_spec.append(("const", v))
            op_idx = len(sched["ops"])
            sched["node_idx"][id(n)] = op_idx
            sched["ops"].append({"method": n._method_name, "args": arg_spec,
                                 "out": None})
        # driver reads the terminal channels
        self._out_chans_names: List[str] = []
        for t in terminals:
            cname = chan_of[id(t)]
            note_reader(cname, "driver")
            self._out_chans_names.append(cname)
        # wire out-channels for ops with readers
        for n in method_nodes:
            cname = chan_of[id(n)]
            if readers_of[cname]:
                actor = self._actor_of(n)
                sched = sched_for(actor)
                sched["ops"][sched["node_idx"][id(n)]]["out"] = cname
        # channel TYPE per edge: same-node parties share a /dev/shm seqlock
        # slot; any cross-node reader switches the channel to the
        # cross-host mailbox tier (reference: shared-memory channels vs the
        # cross-node mutable-object provider). Party placement comes from
        # the GCS actor directory; the driver is its own party.
        import ray_tpu as _rt

        w = _rt._private.worker.global_worker()
        driver_node, driver_addr = w.node_hex, w.address
        placements: Dict[Any, tuple] = {}  # schedule-key -> (node, addr)
        # actors may still be starting at compile time: wait until the GCS
        # has a live placement for each — CONCURRENTLY, so a cold cluster
        # costs max(actor ready time), not the sum
        async def _all_ready():
            import asyncio as _aio

            scheds = list(schedules.values())
            infos = await _aio.gather(*[
                w._gcs_call("WaitActorReady", {
                    "actor_id": s["actor"].actor_id.binary(),
                    "timeout": 120.0}, timeout=130.0)
                for s in scheds])
            return {id(s): r["info"] for s, r in zip(scheds, infos)}

        for sid, info in w._run(_all_ready(), 140.0).items():
            placements[sid] = ((info or {}).get("node_id", ""),
                               (info or {}).get("address", ""))

        def party_place(party):
            if party == "driver":
                return driver_node, driver_addr
            return placements[id(party)]

        writer_of: Dict[str, Any] = {self._input_chan_name: "driver"}
        for n in method_nodes:
            writer_of[chan_of[id(n)]] = sched_for(self._actor_of(n))

        self._chan_specs: Dict[str, dict] = {}
        for cname, readers in readers_of.items():
            if cname != self._input_chan_name and not readers:
                continue
            wnode, _ = party_place(writer_of[cname])
            if any(party_place(p)[0] != wnode or not party_place(p)[0]
                   for p in readers):
                self._chan_specs[cname] = {"type": "xhost"}

        # allocate channels (driver creates shm ones; actors attach).
        # Cross-host channels have no shared segment: each reader owns a
        # mailbox named <chan>@<slot> at its worker; the writer pushes to
        # every mailbox.
        self._channels: List[Any] = []
        self._driver_slots: Dict[str, int] = {}
        for cname, readers in readers_of.items():
            if cname != self._input_chan_name and not readers:
                continue  # unconsumed intermediate: no channel needed
            spec = self._chan_specs.get(cname)
            num = max(1, len(readers))
            if spec is None:
                self._channels.append(Channel(cname, create=True,
                                              num_readers=num))
            else:
                spec["push"] = []
            for slot, party in enumerate(readers):
                if party == "driver":
                    self._driver_slots[cname] = slot
                else:
                    party["chan_readers"][cname] = slot
                if spec is not None:
                    spec["push"].append(
                        (f"{cname}@{slot}", party_place(party)[1]))
        for sched in schedules.values():
            sched["chan_specs"] = {
                c: {"type": "xhost", "push": self._chan_specs[c]["push"]}
                for c in set(list(sched["chan_readers"]) +
                             [op["out"] for op in sched["ops"] if op["out"]])
                if c in self._chan_specs}

        from ray_tpu.dag.channels import open_reader, open_writer

        in_spec = self._chan_specs.get(self._input_chan_name)
        if in_spec is None:
            self._in_chan = next(
                c for c in self._channels
                if getattr(c, "name", "").endswith("_in"))
        else:
            self._in_chan = open_writer(self._input_chan_name, in_spec)
            self._channels.append(self._in_chan)
        self._out_chans: Dict[str, Any] = {}
        for cname in self._out_chans_names:
            self._out_chans[cname] = open_reader(
                cname, self._driver_slots[cname], self._chan_specs.get(cname))
            if self._chan_specs.get(cname) is not None:
                self._channels.append(self._out_chans[cname])
        self._schedules = list(schedules.values())
        # the input channel is fed from a dedicated thread so execute() never
        # blocks the driver when the pipeline is full (the driver must stay
        # free to drain results — otherwise submit-all-then-get deadlocks)
        import queue as _queue
        import threading as _threading

        self._submit_q: "_queue.Queue" = _queue.Queue()
        self._submit_err: Optional[BaseException] = None

        def _feed():
            while True:
                item = self._submit_q.get()
                if item is None:
                    return
                try:
                    self._in_chan.write(item)
                except BaseException as e:
                    self._submit_err = e
                    return

        self._submit_thread = _threading.Thread(
            target=_feed, name="rtpu-dag-submit", daemon=True)
        self._submit_thread.start()

    def _launch_loops(self):
        from ray_tpu.actor import ActorMethod
        from ray_tpu.dag.executor import DAG_LOOP_METHOD

        refs = []
        for sched in self._schedules:
            actor = sched["actor"]
            payload = {"chan_readers": sched["chan_readers"],
                       "chan_specs": sched.get("chan_specs", {}),
                       "ops": sched["ops"]}
            refs.append(ActorMethod(actor, DAG_LOOP_METHOD).remote(payload))
        for r in refs:
            out = ray_tpu.get(r, timeout=120)
            if out != "started":
                raise RuntimeError(f"dag loop failed to start: {out}")

    # -- execution --

    def execute(self, *args, **kwargs):
        if kwargs:
            raise TypeError("compiled DAG execute() takes positional inputs "
                            "only (the plan is index-based)")
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if self._submit_err is not None:
            raise RuntimeError(f"compiled DAG input feed failed: "
                               f"{self._submit_err}")
        self._submit_q.put(tuple(args))
        ref = CompiledDAGRef(self, self._seq)
        self._seq += 1
        return ref

    def _get_result(self, seq: int, timeout: float):
        from ray_tpu.dag.channels import ChannelError, _Stop

        if seq in self._buffer:
            value = self._buffer.pop(seq)
        else:
            while self._results_read <= seq:
                outs = [self._out_chans[c].read(timeout)
                        for c in self._out_chans_names]
                value = outs[0] if self._num_outputs == 1 else outs
                got = self._results_read
                self._results_read += 1
                if got != seq:
                    self._buffer[got] = value
        for v in (value if isinstance(value, list) else [value]):
            if isinstance(v, ChannelError):
                raise RuntimeError(f"compiled DAG stage failed: {v.err}")
            if isinstance(v, _Stop):
                raise RuntimeError("compiled DAG torn down mid-execution")
        return value

    # -- teardown --

    def teardown(self, kill_actors: bool = True):
        from ray_tpu.dag.channels import _Stop

        if self._torn_down:
            return
        self._torn_down = True
        self._submit_q.put(_Stop())  # flows after any queued inputs
        self._submit_q.put(None)  # then stop the feeder thread
        self._submit_thread.join(timeout=30.0)
        # drain the output channels until the sentinel arrives on each:
        # this acks the final stage's _Stop write (so its loop thread exits
        # instead of spinning in an ack wait for its full timeout) and
        # proves propagation through every stage before unlinking
        deadline = time.monotonic() + 30.0
        pending_out = set(self._out_chans_names)
        last_progress = time.monotonic()
        while pending_out and time.monotonic() < deadline:
            progressed = False
            for c in list(pending_out):
                try:
                    v = self._out_chans[c].read(timeout=1.0)
                except Exception:  # raylint: disable=EXC001 drain poll: timeout and writer-death both just mean retry
                    continue
                progressed = True
                if isinstance(v, _Stop):
                    pending_out.discard(c)
            if progressed:
                last_progress = time.monotonic()
            elif time.monotonic() - last_progress > 3.0:
                break  # a dead stage will never flush its sentinel
        for ch in self._channels:
            try:
                ch.close(unlink=True)
            except Exception:  # raylint: disable=EXC001 teardown: segment may already be unlinked by a peer
                pass
        if kill_actors:
            for node in self._order:
                if isinstance(node, ClassMethodNode) \
                        and isinstance(node._target, ClassNode) \
                        and node._target._actor_handle is not None:
                    try:
                        ray_tpu.kill(node._target._actor_handle)
                    except Exception:  # raylint: disable=EXC001 teardown: actor may already be dead
                        pass
                    node._target._actor_handle = None

"""DAG authoring + compiled execution (reference: python/ray/dag).

``fn.bind(...)`` / ``Actor.bind(...)`` / ``handle.method.bind(...)`` build a
lazy graph (dag_node.py, class_node.py, input_node.py); ``execute`` walks it;
``experimental_compile`` (dag_node.py:279) returns a ``CompiledDAG`` with a
precomputed topological schedule.

Round-1 scope note: the compiled path pre-resolves the schedule and reuses
pickled task payloads, but still rides the normal actor-call RPC plane; the
shared-memory mutable-object channel data plane (reference:
experimental/channel/shared_memory_channel.py + the seqlock C++ side) is the
next tier of this module (see channels.py for the channel primitives).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.object_ref import ObjectRef


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _deps(self) -> List["DAGNode"]:
        out = []
        for v in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(v, DAGNode):
                out.append(v)
        return out

    def execute(self, *input_args, **input_kwargs):
        """Eagerly execute the graph rooted here; returns an ObjectRef."""
        cache: Dict[int, Any] = {}
        return self._execute_node(input_args, input_kwargs, cache)

    def _resolve(self, v, input_args, input_kwargs, cache):
        if isinstance(v, DAGNode):
            return v._execute_node(input_args, input_kwargs, cache)
        return v

    def _resolved_args(self, input_args, input_kwargs, cache):
        args = [self._resolve(a, input_args, input_kwargs, cache)
                for a in self._bound_args]
        kwargs = {k: self._resolve(v, input_args, input_kwargs, cache)
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_node(self, input_args, input_kwargs, cache):
        raise NotImplementedError

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)


class InputNode(DAGNode):
    """Placeholder for the value passed to execute() (reference:
    dag/input_node.py). Supports `with InputNode() as inp:` authoring."""

    def __init__(self, index: int = 0):
        super().__init__((), {})
        self._index = index

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_node(self, input_args, input_kwargs, cache):
        return input_args[self._index]


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_node(self, input_args, input_kwargs, cache):
        key = id(self)
        if key not in cache:
            args, kwargs = self._resolved_args(input_args, input_kwargs, cache)
            cache[key] = self._remote_fn.remote(*args, **kwargs)
        return cache[key]


class ClassNode(DAGNode):
    """Actor construction in a DAG; instantiated once per compiled graph."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._actor_handle = None

    def _get_actor(self, input_args, input_kwargs, cache):
        if self._actor_handle is None:
            args, kwargs = self._resolved_args(input_args, input_kwargs, cache)
            args = [ray_tpu.get(a) if isinstance(a, ObjectRef) else a for a in args]
            self._actor_handle = self._actor_cls.remote(*args, **kwargs)
        return self._actor_handle

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)

    def _execute_node(self, input_args, input_kwargs, cache):
        return self._get_actor(input_args, input_kwargs, cache)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, actor_or_node, method_name, args, kwargs):
        super().__init__(args, kwargs)
        self._target = actor_or_node
        self._method_name = method_name

    def _execute_node(self, input_args, input_kwargs, cache):
        key = id(self)
        if key not in cache:
            if isinstance(self._target, ClassNode):
                handle = self._target._get_actor(input_args, input_kwargs, cache)
            else:
                handle = self._target
            args, kwargs = self._resolved_args(input_args, input_kwargs, cache)
            method = getattr(handle, self._method_name)
            cache[key] = method.remote(*args, **kwargs)
        return cache[key]


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _execute_node(self, input_args, input_kwargs, cache):
        return [self._resolve(o, input_args, input_kwargs, cache)
                for o in self._bound_args]


class CompiledDAG:
    """Precompiled schedule: topological order fixed once, actors created
    eagerly (reference: compiled_dag_node.py:805; execute :2546)."""

    def __init__(self, root: DAGNode):
        self._root = root
        self._order = self._toposort(root)
        # instantiate all actors up front
        for node in self._order:
            if isinstance(node, ClassNode):
                node._get_actor((), {}, {})

    @staticmethod
    def _toposort(root) -> List[DAGNode]:
        seen: List[DAGNode] = []
        visiting = set()

        def visit(n: DAGNode):
            if id(n) in visiting:
                raise ValueError("cycle in DAG")
            if n in seen:
                return
            visiting.add(id(n))
            for d in n._deps():
                visit(d)
            visiting.discard(id(n))
            seen.append(n)

        visit(root)
        return seen

    def execute(self, *args, **kwargs):
        cache: Dict[int, Any] = {}
        return self._root._execute_node(args, kwargs, cache)

    def teardown(self):
        for node in self._order:
            if isinstance(node, ClassNode) and node._actor_handle is not None:
                try:
                    ray_tpu.kill(node._actor_handle)
                except Exception:
                    pass
                node._actor_handle = None

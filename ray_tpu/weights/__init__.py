"""ray_tpu.weights: mesh-aware sharded weight transfer and live resharding.

The weight plane moves sharded model state between actors — learner ->
env-runners, train mesh -> serve replicas, old mesh -> re-formed elastic
mesh — without ever materializing a full array on one host. See
``ray_tpu/weights/README.md`` for the design.

Public surface::

    from ray_tpu import weights

    spec = weights.ShardedTreeSpec.from_tree(tree, mesh, parts={...})
    plan = weights.plan_reshard(src_spec, dst_spec)   # inspectable
    store = weights.WeightStore("policy")             # named, in GCS
    v = store.publish(tree)                           # broadcast source
    weights.publish_host_shards(store, v2, spec, host, shards)  # mesh source
    tree = store.pull()                               # replicated consumer
    shards = store.pull_shards(dst_spec, host)        # sharded consumer
    sub = store.subscribe(); sub.poll(timeout=10)     # long-poll updates
"""

# Lazy exports (PEP 562): wire.py registers MeshSpec/TransferEdge on first
# control-plane encode in EVERY process, which imports this package — the
# store/transport tiers (and their numpy import) must not ride along into
# processes that never move weights.
_EXPORTS = {
    "TransferEdge": "plan", "TransferPlan": "plan", "plan_reshard": "plan",
    "DcnCostModel": "plan", "RedistributionProgram": "plan",
    "ReshardLoweringError": "plan", "lower_collective": "plan",
    "maybe_lower_collective": "plan", "lowering_fallback_counts": "plan",
    "MeshSpec": "spec", "ShardedTreeSpec": "spec",
    "flatten_tree": "spec", "unflatten_tree": "spec",
    "WeightStore": "store", "WeightStoreActor": "store",
    "WeightSubscription": "store",
    "load_durable": "store", "durable_versions": "store",
    "collective_reshard": "transport", "jax_reshard": "transport",
    "local_shards_of": "transport", "publish_host_shards": "transport",
    "pull_with_locals": "transport", "redistribute": "transport",
    "reshard_lowering_stats": "transport",
}


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module 'ray_tpu.weights' has no attribute "
                             f"{name!r}")
    import importlib

    return getattr(importlib.import_module(f"ray_tpu.weights.{mod}"), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


__all__ = [
    "MeshSpec",
    "ShardedTreeSpec",
    "TransferEdge",
    "TransferPlan",
    "WeightStore",
    "WeightStoreActor",
    "WeightSubscription",
    "load_durable",
    "durable_versions",
    "plan_reshard",
    "DcnCostModel",
    "RedistributionProgram",
    "ReshardLoweringError",
    "lower_collective",
    "maybe_lower_collective",
    "lowering_fallback_counts",
    "flatten_tree",
    "unflatten_tree",
    "local_shards_of",
    "publish_host_shards",
    "pull_with_locals",
    "collective_reshard",
    "jax_reshard",
    "redistribute",
    "reshard_lowering_stats",
]

"""Versioned WeightStore: a named handle for sharded weight hand-off.

Reference: the reference ships weights learner->workers through
``ray.put`` + polling named actors (rllib) or NCCL broadcast groups; here
the hand-off is a first-class, versioned control point:

- ``WeightStoreActor`` is a named (GCS-registered), detached actor holding
  per-version chunk manifests. A chunk is one planner box of one leaf.
- Publishers either ship chunk BYTES to the actor, which re-``put``s them so
  the refs are owned by the store and survive publisher death
  (``durable=True`` — the elastic re-form path), or ``put`` chunks
  themselves and register only refs (``durable=False`` — zero extra copy;
  the learner-broadcast fast path, valid while the publisher lives).
- Consumers ``pull(version)`` a full tree (broadcast) or
  ``pull_shards(version, dst_spec, host)`` — only the chunks intersecting
  their destination boxes cross the wire, never a gathered array.
- ``subscribe()`` long-polls the actor for commits, giving N consumers a
  push-shaped broadcast without busy polling.

Version monotonicity: versions are ints; ``commit`` refuses to move
``latest`` backwards, and subscriptions only ever surface strictly newer
versions. Per-version transfer stats (bytes published/pulled, edges,
fan-out) are mirrored to the GCS KV ``weights`` namespace for the
dashboard's ``/api/weights``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.weights.spec import (
    Box,
    MeshSpec,
    ShardedTreeSpec,
    box_slices,
    flatten_tree,
    unflatten_tree,
)

_STORE_PREFIX = "rtpu_weight_store:"
_KEEP_VERSIONS = 2  # committed versions retained (older chunks freed)

_obs_lock = threading.Lock()
_obs_metrics: Optional[dict] = None


def _obs() -> dict:
    """Lazily-created weight-plane metrics on the shared registry (always
    on: every publish/pull edge lands in ``/metrics``)."""
    global _obs_metrics
    with _obs_lock:
        if _obs_metrics is None:
            from ray_tpu.util.metrics import Histogram

            bounds = [0.01, 0.1, 1, 10, 100]
            _obs_metrics = {
                "publish": Histogram(
                    "ray_tpu.weights.publish_seconds",
                    "one publisher's chunk publish into a weight store",
                    boundaries=bounds),
                "pull": Histogram(
                    "ray_tpu.weights.pull_seconds",
                    "one consumer's chunk pull/assembly from a weight "
                    "store", boundaries=bounds),
                "reshard": Histogram(
                    "ray_tpu.weights.reshard_seconds",
                    "collective/XLA-tier reshard execution",
                    boundaries=bounds),
            }
        return _obs_metrics


def _encode_box(box: Box) -> str:
    return ",".join(f"{a}:{b}" for a, b in box)


def _decode_box(s: str) -> Box:
    if not s:
        return ()
    return tuple(tuple(int(x) for x in part.split(":")) for part in s.split(","))


def _chunk_key(leaf: str, box: Box) -> str:
    return f"{leaf}|{_encode_box(box)}"


def _decode_chunk(val: Any, chunk_entry: dict) -> np.ndarray:
    """Undo a chunk's manifest-recorded quantized encoding (a no-op for
    plain chunks) — pulls are transparent to the publisher's codec."""
    enc = chunk_entry.get("enc")
    if enc is None:
        return np.asarray(val)
    from ray_tpu.collective.quant import decode_array

    return decode_array(np.asarray(val), enc)


def _split_key(key: str) -> Tuple[str, Box]:
    leaf, _, flat = key.rpartition("|")
    return leaf, _decode_box(flat)


def _spec_payload(spec: ShardedTreeSpec) -> dict:
    return {
        "mesh": {"shape": list(spec.mesh.shape),
                 "axis_names": list(spec.mesh.axis_names),
                 "hosts": list(spec.mesh.hosts)},
        "parts": {k: list(v) for k, v in spec.parts.items()},
        "meta": {k: [list(shape), dtype] for k, (shape, dtype) in
                 spec.meta.items()},
    }


def _spec_from_payload(d: dict) -> ShardedTreeSpec:
    m = d["mesh"]
    return ShardedTreeSpec(
        mesh=MeshSpec(tuple(m["shape"]), tuple(m["axis_names"]),
                      tuple(m["hosts"])),
        parts={k: tuple(v) for k, v in d["parts"].items()},
        meta={k: (tuple(v[0]), v[1]) for k, v in d["meta"].items()},
    )


class WeightStoreActor:
    """Named actor holding versioned chunk manifests (sync methods run on
    executor threads, so object-plane calls are safe; only ``poll`` is
    async and costs no thread while parked)."""

    def __init__(self, name: str, durable_root: Optional[str] = None):
        self.name = name
        self._versions: Dict[int, dict] = {}
        self._latest = -1
        self._counter = 0
        # optional cold tier: durable publishes additionally persist as
        # PINNED checkpoint-plane manifests under this root (and ride a
        # TieredStore's remote backend when the root is tiered) — a
        # committed durable version then survives not just publisher
        # death but full-cluster death
        self._durable_root = durable_root
        self._dstore: Optional[Any] = None

    # -- publish side --------------------------------------------------

    def next_version(self) -> int:
        self._counter = max(self._counter, self._latest) + 1
        return self._counter

    def begin(self, version: int, skeleton: Any, spec_payload: dict,
              num_chunks: int) -> bool:
        """Open ``version`` for publishing. Idempotent across the source
        hosts (each calls begin with the same deterministic arguments)."""
        v = self._versions.get(version)
        if v is None and version <= self._latest:
            # a KNOWN version may be re-begun (a publisher whose plan gave
            # it zero chunks can arrive after the commit); an unknown one
            # below latest is a real monotonicity violation
            raise ValueError(
                f"version {version} not monotonic (latest is {self._latest})")
        if v is None:
            self._versions[version] = {
                "skeleton": skeleton, "spec": spec_payload,
                "num_chunks": int(num_chunks), "chunks": {},
                "committed": False, "ts": time.time(),
                "bytes_published": 0, "bytes_pulled": 0, "num_pulls": 0,
                "bytes_reused": 0,
            }
        return True

    def put_chunks(self, version: int, blobs: Dict[str, Any],
                   meta: Optional[Dict[str, dict]] = None) -> int:
        """Durable path: chunk bytes arrive as args; re-put them so the
        refs are OWNED by this actor and outlive the publisher. ``meta``
        carries per-key ``{"sha", "enc", "raw_nbytes"}`` — the content
        address (delta base) and the quantized encoding (pulls decode
        transparently)."""
        v = self._versions[version]
        meta = meta or {}
        for key, arr in blobs.items():
            if key in v["chunks"]:
                continue
            arr = np.asarray(arr)
            m = meta.get(key, {})
            v["chunks"][key] = {"ref": ray_tpu.put(arr),
                                "nbytes": arr.nbytes,
                                "dtype": arr.dtype.str,
                                "sha": m.get("sha", ""),
                                "enc": m.get("enc"),
                                "owned": True,
                                "raw_nbytes": int(m.get("raw_nbytes",
                                                        arr.nbytes))}
            v["bytes_published"] += arr.nbytes
        self._maybe_commit(version)
        return len(v["chunks"])

    def register_chunks(self, version: int,
                        refs: Dict[str, List[Any]],
                        nbytes: Dict[str, int],
                        dtypes: Dict[str, str],
                        meta: Optional[Dict[str, dict]] = None) -> int:
        """Zero-copy path: the publisher ``put`` the chunks; we only hold
        the refs (valid while the publisher's owner process lives)."""
        v = self._versions[version]
        meta = meta or {}
        for key, boxed_ref in refs.items():
            if key in v["chunks"]:
                continue
            m = meta.get(key, {})
            v["chunks"][key] = {"ref": boxed_ref[0],
                                "nbytes": int(nbytes[key]),
                                "dtype": dtypes[key],
                                "sha": m.get("sha", ""),
                                "enc": m.get("enc"),
                                "owned": False,
                                "raw_nbytes": int(m.get("raw_nbytes",
                                                        nbytes[key]))}
            v["bytes_published"] += int(nbytes[key])
        self._maybe_commit(version)
        return len(v["chunks"])

    def chunk_shas(self, version: int) -> Dict[str, Tuple[str, Optional[str]]]:
        """Per-chunk ``(raw-byte sha, stored encoding spec or None)`` of a
        committed version (the delta base) — the publisher needs BOTH: a
        sha match alone is not enough to alias a chunk whose stored bytes
        are a lossy encoding of those raw bytes. Raises for unknown/
        uncommitted/retired versions — the publisher falls back to a full
        publish."""
        v = self._versions.get(version)
        if v is None or not v["committed"] or v.get("retired"):
            raise KeyError(
                f"weight store {self.name!r} version {version} is not "
                f"available as a delta base (unknown, uncommitted or "
                f"retired)")
        out = {}
        for k, c in v["chunks"].items():
            enc = c.get("enc")
            spec = f"{enc['codec']}:{enc['block']}" if enc else None
            out[k] = (c.get("sha", ""), spec)
        return out

    def reuse_chunks(self, version: int, keys: List[str],
                     from_version: int, durable: bool = False) -> int:
        """Delta publish: alias ``keys`` of ``from_version`` into
        ``version`` by content address — the chunk refs are shared, so no
        bytes move and retention of the SOURCE version later cannot
        invalidate them (the entry copies keep the refs alive). A
        ``durable`` target must OWN every chunk: refs borrowed from a
        zero-copy (non-durable) base are re-put here, or the durable
        guarantee would silently die with the base's publisher process."""
        v = self._versions[version]
        src = self._versions.get(from_version)
        if src is None or src.get("retired"):
            raise KeyError(f"delta base version {from_version} is gone")
        reused = 0
        for key in keys:
            if key in v["chunks"]:
                continue
            c = src["chunks"].get(key)
            if c is None:
                raise KeyError(
                    f"delta base version {from_version} has no chunk "
                    f"{key!r}")
            ent = dict(c)
            if durable and not c.get("owned"):
                ent["ref"] = ray_tpu.put(np.asarray(ray_tpu.get(c["ref"])))
                ent["owned"] = True
            v["chunks"][key] = ent
            v["bytes_reused"] += int(c.get("raw_nbytes", c["nbytes"]))
            reused += 1
        self._maybe_commit(version)
        return reused

    def _maybe_commit(self, version: int):
        v = self._versions[version]
        if v["committed"] or len(v["chunks"]) < v["num_chunks"]:
            return
        v["committed"] = True
        if version > self._latest:
            self._latest = version
        self._persist_durable(version)
        # bound retention: drop chunk refs of superseded versions (the
        # refcounter frees owned objects once nothing borrows them)
        committed = sorted(k for k, vv in self._versions.items()
                           if vv["committed"])
        for old in committed[:-_KEEP_VERSIONS]:
            if not self._versions[old].get("retired"):
                self._versions[old]["chunks"] = {}
                self._versions[old]["retired"] = True
                self._retire_durable(old)
        self._push_stats()

    # -- durable cold tier (checkpoint-plane persistence) --------------

    def _durable_store(self):
        """Lazy handle on the cold-tier store: a TieredStore when the
        root carries a TIER descriptor (durable versions then mirror to
        the remote chunk backend), a plain CheckpointStore otherwise."""
        if self._durable_root is None:
            return None
        if self._dstore is None:
            import os

            from ray_tpu.ckpt.store import CheckpointStore
            from ray_tpu.ckpt.tier.tiered import TIER_FILE, TieredStore

            root = self._durable_root
            if os.path.exists(os.path.join(root, TIER_FILE)):
                self._dstore = TieredStore(root, name=f"weights-{self.name}")
            else:
                self._dstore = CheckpointStore(
                    root, name=f"weights-{self.name}")
        return self._dstore

    def _durable_ckpt_id(self, version: int) -> str:
        return f"weights-{self.name}-v{int(version):010d}"

    def _persist_durable(self, version: int):
        """Mirror a fully-owned committed version into the checkpoint
        plane as a PINNED manifest (``weights-<name>-v<version>``): each
        chunk's stored (possibly quantized) bytes land content-addressed
        in the chunk pool, geometry/encoding ride the manifest stats, and
        the pin keeps retention and the cluster sweeper off the version
        until :meth:`_retire_durable` releases it. Versions holding any
        borrowed (zero-copy) ref are skipped — those bytes die with their
        publisher, so persisting them would fake durability. Best-effort
        by contract: cold-tier trouble must never fail a publish."""
        store = self._durable_store()
        if store is None:
            return
        v = self._versions[version]
        if not v["chunks"] or any(not c.get("owned")
                                  for c in v["chunks"].values()):
            return
        try:
            from ray_tpu.ckpt import manifest as mf

            leaves: Dict[str, Any] = {}
            chunk_meta: Dict[str, dict] = {}
            for key, c in sorted(v["chunks"].items()):
                arr = np.ascontiguousarray(np.asarray(ray_tpu.get(c["ref"])))
                data = arr.tobytes()
                h, _created = mf.write_chunk(store.root, data)
                # the manifest leaf is the stored byte payload (flat
                # uint8, like a file leaf); real geometry + encoding live
                # in stats so load_durable can rebuild the exact arrays
                leaves[key] = mf.LeafEntry(
                    kind=mf.ND, shape=(len(data),), dtype="|u1",
                    chunks={mf.encode_box(((0, len(data)),)):
                            (h, len(data))})
                chunk_meta[key] = {
                    "dtype": arr.dtype.str, "shape": list(arr.shape),
                    "enc": c.get("enc"), "sha": c.get("sha", ""),
                    "raw_nbytes": int(c.get("raw_nbytes", arr.nbytes))}
            cid = self._durable_ckpt_id(version)
            man = mf.Manifest(
                ckpt_id=cid, step=int(version), ts=time.time(),
                parent=None, skeleton=v["skeleton"], spec=v["spec"],
                leaves=leaves,
                stats={"weights_store": self.name,
                       "weights_version": int(version),
                       "chunks": chunk_meta})
            # write + pin, WITHOUT moving LATEST: the root may be shared
            # with a training checkpoint store whose restore-latest
            # semantics a weight publish must not hijack
            mf.write_manifest(store.root, man)
            store.pin(cid)
            enqueue = getattr(store, "enqueue_mirror", None)
            if enqueue is not None:
                enqueue(cid)
            v["durable_ckpt_id"] = cid
        except Exception as e:  # cold tier is best-effort by contract
            import logging

            logging.getLogger(__name__).warning(
                "weight store %s: durable persist of v%s failed: %r",
                self.name, version, e)

    def _retire_durable(self, version: int):
        """Unpin a retired version's cold-tier manifest so retention /
        the cluster sweeper may reclaim it (shared chunks stay as long
        as any live manifest references them)."""
        store = self._dstore  # never constructed just to unpin
        cid = self._versions[version].pop("durable_ckpt_id", None)
        if store is None or cid is None:
            return
        try:
            store.unpin(cid)
        except Exception:  # cold tier is best-effort by contract
            pass

    def note_pull(self, version: int, nbytes: int) -> bool:
        v = self._versions.get(version)
        if v is not None:
            v["bytes_pulled"] += int(nbytes)
            v["num_pulls"] += 1
        return True

    # -- consume side --------------------------------------------------

    def latest(self) -> int:
        return self._latest

    def manifest(self, version: Optional[int] = None) -> dict:
        if version is None:
            version = self._latest
        if version < 0 or version not in self._versions:
            raise KeyError(f"weight store {self.name!r} has no version "
                           f"{version}")
        v = self._versions[version]
        if not v["committed"]:
            raise KeyError(f"version {version} is not committed yet")
        if v.get("retired"):
            raise KeyError(f"version {version} was retired "
                           f"(keep={_KEEP_VERSIONS})")
        return {
            "version": version,
            "skeleton": v["skeleton"],
            "spec": v["spec"],
            "chunks": {k: {"ref": [c["ref"]], "nbytes": c["nbytes"],
                           "dtype": c["dtype"], "sha": c.get("sha", ""),
                           "enc": c.get("enc")}
                       for k, c in v["chunks"].items()},
        }

    async def poll(self, after_version: int, timeout: float = 25.0) -> int:
        """Long-poll: resolves with ``latest`` once it exceeds
        ``after_version`` (or on timeout, with the current latest)."""
        import asyncio

        deadline = time.monotonic() + timeout
        while self._latest <= after_version and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        return self._latest

    def stats(self) -> dict:
        out = {
            "name": self.name,
            "latest": self._latest,
            "versions": {
                str(ver): {k: v.get(k, 0) for k in
                           ("committed", "ts", "num_chunks",
                            "bytes_published", "bytes_pulled", "num_pulls",
                            "bytes_reused", "durable_ckpt_id")}
                for ver, v in sorted(self._versions.items())
            },
        }
        if self._durable_root is not None:
            out["durable_root"] = self._durable_root
        return out

    def _push_stats(self):
        """Mirror stats into the GCS KV (``weights`` ns) for the dashboard.
        Best-effort: stats must never fail a publish."""
        try:
            from ray_tpu._private import wire
            from ray_tpu.experimental.internal_kv import _internal_kv_put

            _internal_kv_put(self.name.encode(), wire.dumps(self.stats()),
                             namespace="weights")
        except Exception:  # stats mirror is best-effort by contract
            pass


class WeightSubscription:
    """Consumer-side cursor over a store's committed versions."""

    def __init__(self, store: "WeightStore", start_after: int = -1):
        self._store = store
        self.last_version = start_after

    def poll(self, timeout: float = 0.0):
        """Return ``(version, tree)`` for the newest committed version
        strictly after the last one seen, or None. ``timeout`` > 0 long-polls
        on the store actor (costing no thread there)."""
        latest = self._store.poll_latest(self.last_version, timeout=timeout)
        if latest <= self.last_version:
            return None
        tree, version = self._store.pull(return_version=True)
        if version <= self.last_version:
            return None
        self.last_version = version
        return version, tree

    def poll_shards(self, dst_spec: ShardedTreeSpec, host: str,
                    timeout: float = 0.0):
        """Sharded flavor: returns ``(version, {leaf: {box: array}})``."""
        latest = self._store.poll_latest(self.last_version, timeout=timeout)
        if latest <= self.last_version:
            return None
        shards, version = self._store.pull_shards(
            dst_spec, host, return_version=True)
        if version <= self.last_version:
            return None
        self.last_version = version
        return version, shards


class WeightStore:
    """Process-local handle on a named weight store (create-or-attach)."""

    def __init__(self, name: str, create: bool = True,
                 durable_root: Optional[str] = None):
        self.name = name
        actor_name = _STORE_PREFIX + name
        if create:
            actor_cls = ray_tpu.remote(WeightStoreActor)
            self._actor = actor_cls.options(
                name=actor_name, lifetime="detached", get_if_exists=True,
                max_concurrency=32, num_cpus=0.1).remote(
                    name, durable_root)
        else:
            self._actor = ray_tpu.get_actor(actor_name)

    # -- publish -------------------------------------------------------

    def next_version(self) -> int:
        return ray_tpu.get(self._actor.next_version.remote(), timeout=60)

    def publish(self, tree: Any, *, version: Optional[int] = None,
                spec: Optional[ShardedTreeSpec] = None,
                durable: bool = False, timeout: float = 300.0,
                delta_from: Optional[int] = None,
                compression: Any = None) -> int:
        """Publish a FULL tree from this process (the single-source case:
        a learner broadcasting to env-runners, a driver seeding replicas).
        For mesh-sharded publishers use :func:`publish_host_shards`.

        ``delta_from=prev_version`` hashes every leaf chunk against the
        previous manifest and ships ONLY the changed ones — unchanged
        leaves alias the prior version's chunks by content address (no
        bytes move; pulls are byte-exact regardless). A vanished/retired
        base falls back to a full publish, logged, never an error.

        ``compression`` ("int8"/"fp8"/"bf16", collective/quant.py)
        block-quantizes the chunk payloads on the wire; the encoding is
        recorded per chunk in the manifest and ``pull``/``pull_shards``
        decode transparently (lossy — delta hashing still uses the RAW
        bytes, so delta and quantized publishes compose)."""
        skeleton, leaves = flatten_tree(tree)
        arrays = {p: np.asarray(v) for p, v in leaves.items()}
        if spec is None:
            spec = ShardedTreeSpec.from_tree(tree, MeshSpec.host_mesh(["src"]))
        if version is None:
            version = self.next_version()
        chunks = {_chunk_key(p, tuple((0, s) for s in a.shape)): a
                  for p, a in arrays.items()}
        self._publish_chunks(version, skeleton, spec, chunks,
                             num_chunks=len(chunks), durable=durable,
                             timeout=timeout, delta_from=delta_from,
                             compression=compression)
        return version

    def _publish_chunks(self, version: int, skeleton: Any,
                        spec: ShardedTreeSpec, chunks: Dict[str, np.ndarray],
                        num_chunks: int, durable: bool, timeout: float,
                        delta_from: Optional[int] = None,
                        compression: Any = None):
        import hashlib

        from ray_tpu.collective.quant import encode_array, resolve_codec
        from ray_tpu.util import tracing

        codec = resolve_codec(compression)
        t0 = time.perf_counter()
        with tracing.profile("weights.publish", category="weights",
                             store=self.name, version=version):
            # hash the array buffer directly — tobytes() would copy every
            # chunk; ascontiguousarray is a no-op for the (typical)
            # already-contiguous case. Hashing on EVERY publish is what
            # lets any version serve as a later delta base. The dtype
            # prefixes the digest: identical bytes under a different
            # dtype are a DIFFERENT chunk (aliasing one would value-cast
            # on pull).
            def _sha(a: np.ndarray) -> str:
                h = hashlib.sha256(a.dtype.str.encode())
                h.update(np.ascontiguousarray(a))
                return h.hexdigest()

            shas = {k: _sha(a) for k, a in chunks.items()}
            ray_tpu.get(self._actor.begin.remote(
                version, skeleton, _spec_payload(spec), num_chunks),
                timeout=timeout)
            todo = dict(chunks)
            if delta_from is not None:
                # any base-unavailable condition (retired by retention,
                # unknown version, a race against retirement mid-reuse —
                # surfaced as a wrapped TaskError) degrades to a FULL
                # publish: correctness never depends on the delta base
                try:
                    prev = ray_tpu.get(
                        self._actor.chunk_shas.remote(delta_from),
                        timeout=timeout)
                    cspec = codec.spec() if codec is not None else None

                    def _reusable(k: str) -> bool:
                        ent = prev.get(k)
                        if ent is None or ent[0] != shas[k]:
                            return False
                        if ent[1] is None:
                            return True  # base chunk is exact raw bytes
                        # the base chunk is a LOSSY encoding of the same
                        # raw bytes: aliasing it is only correct when this
                        # publish would encode the chunk identically (the
                        # codecs are deterministic) — never under a
                        # different codec or an exact (compression=None)
                        # publish, whose pulls must stay byte-exact
                        return (ent[1] == cspec and
                                np.issubdtype(chunks[k].dtype,
                                              np.floating))

                    unchanged = [k for k in shas if _reusable(k)]
                    if unchanged:
                        ray_tpu.get(self._actor.reuse_chunks.remote(
                            version, unchanged, delta_from, durable),
                            timeout=timeout)
                        for k in unchanged:
                            todo.pop(k)
                except Exception as e:
                    import logging

                    logging.getLogger(__name__).warning(
                        "weight store %s: delta base v%s unavailable "
                        "(%s); publishing v%s in full", self.name,
                        delta_from, e, version)
            payloads: Dict[str, np.ndarray] = {}
            meta: Dict[str, dict] = {}
            for k, a in todo.items():
                m = {"sha": shas[k], "enc": None, "raw_nbytes": int(a.nbytes)}
                if codec is not None and np.issubdtype(a.dtype, np.floating):
                    wire, enc = encode_array(a, codec)
                    payloads[k] = wire
                    m["enc"] = enc
                else:
                    payloads[k] = a
                meta[k] = m
            if durable:
                # ship bytes; the store re-puts so refs survive this process
                ray_tpu.get(self._actor.put_chunks.remote(
                    version, payloads, meta), timeout=timeout)
            else:
                refs = {k: [ray_tpu.put(a)] for k, a in payloads.items()}
                nbytes = {k: int(a.nbytes) for k, a in payloads.items()}
                dtypes = {k: a.dtype.str for k, a in payloads.items()}
                ray_tpu.get(self._actor.register_chunks.remote(
                    version, refs, nbytes, dtypes, meta), timeout=timeout)
        _obs()["publish"].observe(time.perf_counter() - t0)

    # -- consume -------------------------------------------------------

    def latest(self) -> int:
        return ray_tpu.get(self._actor.latest.remote(), timeout=60)

    def poll_latest(self, after_version: int, timeout: float = 0.0) -> int:
        if timeout <= 0:
            return self.latest()
        return ray_tpu.get(
            self._actor.poll.remote(after_version, timeout),
            timeout=timeout + 30)

    def manifest(self, version: Optional[int] = None) -> dict:
        return ray_tpu.get(self._actor.manifest.remote(version), timeout=120)

    def pull(self, version: Optional[int] = None, *,
             return_version: bool = False, timeout: float = 300.0):
        """Assemble the FULL tree of ``version`` (default: latest). Only
        for replicated consumers — sharded consumers use
        :meth:`pull_shards` and never hold a gathered array."""
        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        with tracing.profile("weights.pull", category="weights",
                             store=self.name):
            man = self.manifest(version)
            leaves: Dict[str, np.ndarray] = {}
            spec = _spec_from_payload(man["spec"])
            pulled = 0
            by_leaf: Dict[str, List[Tuple[Box, dict]]] = {}
            for key, c in man["chunks"].items():
                leaf, box = _split_key(key)
                by_leaf.setdefault(leaf, []).append((box, c))
            for leaf, (shape, dtype) in spec.meta.items():
                out = np.empty(shape, dtype=np.dtype(dtype))
                for box, c in by_leaf.get(leaf, ()):
                    val = _decode_chunk(
                        ray_tpu.get(c["ref"][0], timeout=timeout), c)
                    out[box_slices(box)] = val.reshape(
                        tuple(b - a for a, b in box))
                    pulled += c["nbytes"]
                leaves[leaf] = out
            self._actor.note_pull.remote(man["version"], pulled)
            tree = unflatten_tree(man["skeleton"], leaves)
        _obs()["pull"].observe(time.perf_counter() - t0)
        return (tree, man["version"]) if return_version else tree

    def pull_shards(self, dst_spec: ShardedTreeSpec, host: str,
                    version: Optional[int] = None, *,
                    return_version: bool = False, timeout: float = 300.0):
        """Pull exactly this host's destination shards, assembling each from
        the intersecting published chunks. Returns
        ``{leaf: {dst_box: array}}``; never materializes a full leaf unless
        the destination box IS the full leaf."""
        from ray_tpu.util import tracing
        from ray_tpu.weights.spec import (host_boxes, intersect_box,
                                          rel_slices)

        t0 = time.perf_counter()
        with tracing.profile("weights.pull", category="weights",
                             store=self.name, host=host):
            man = self.manifest(version)
            spec = _spec_from_payload(man["spec"])
            by_leaf: Dict[str, List[Tuple[Box, dict]]] = {}
            for key, c in man["chunks"].items():
                leaf, box = _split_key(key)
                by_leaf.setdefault(leaf, []).append((box, c))
            out: Dict[str, Dict[Box, np.ndarray]] = {}
            pulled = 0
            cache: Dict[str, np.ndarray] = {}
            for leaf, (shape, dtype) in dst_spec.meta.items():
                dt = np.dtype(dtype)
                out[leaf] = {}
                for dbox in host_boxes(dst_spec.mesh, dst_spec.part_of(leaf),
                                       shape, host):
                    shard = np.empty(tuple(b - a for a, b in dbox), dtype=dt)
                    for cbox, c in by_leaf.get(leaf, ()):
                        inter = intersect_box(dbox, cbox)
                        if inter is None:
                            continue
                        key = _chunk_key(leaf, cbox)
                        chunk = cache.get(key)
                        if chunk is None:
                            chunk = _decode_chunk(
                                ray_tpu.get(c["ref"][0], timeout=timeout), c
                            ).reshape(tuple(b - a for a, b in cbox))
                            cache[key] = chunk
                            pulled += c["nbytes"]
                        shard[rel_slices(inter, dbox)] = chunk[
                            rel_slices(inter, cbox)]
                    out[leaf][dbox] = shard
            self._actor.note_pull.remote(man["version"], pulled)
        _obs()["pull"].observe(time.perf_counter() - t0)
        return (out, man["version"]) if return_version else out

    def subscribe(self, start_after: Optional[int] = None
                  ) -> WeightSubscription:
        return WeightSubscription(
            self, self.latest() if start_after is None else start_after)

    def stats(self) -> dict:
        return ray_tpu.get(self._actor.stats.remote(), timeout=60)

    def shutdown(self):
        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# cold-tier restore (no actor, no cluster): the full-restart path
# ---------------------------------------------------------------------------


def _attach_durable(root: str):
    """Store handle on a durable-weights root: tiered when the root
    carries a TIER descriptor (read-through to the remote backend, no
    mirror pump), plain otherwise."""
    import os

    from ray_tpu.ckpt.store import CheckpointStore
    from ray_tpu.ckpt.tier.tiered import TIER_FILE, TieredStore

    if os.path.exists(os.path.join(root, TIER_FILE)):
        return TieredStore(root, mirror=False)
    return CheckpointStore(root)


def _durable_index(store, name: Optional[str]) -> Dict[int, str]:
    out: Dict[int, str] = {}
    for man in store.list():
        st = man.stats or {}
        if "weights_version" not in st:
            continue  # a training checkpoint sharing the root
        if name is not None and st.get("weights_store") != name:
            continue
        out[int(st["weights_version"])] = man.ckpt_id
    return out


def durable_versions(root: str, name: Optional[str] = None) -> Dict[int, str]:
    """Durable weight versions persisted under ``root`` as
    ``{version: ckpt_id}`` — optionally filtered to one store ``name``
    (a root may hold several stores, and training checkpoints besides)."""
    return _durable_index(_attach_durable(root), name)


def load_durable(root: str, name: Optional[str] = None,
                 version: Optional[int] = None) -> Tuple[int, Any]:
    """Rebuild a durable weight version from its cold-tier manifest with
    NO store actor (and no cluster) alive — the full-restart path of
    ``publish(..., durable=True)`` on a store with a ``durable_root``.
    Chunk bytes read through the storage tiers (an evicted local pool
    fetches from the remote backend, sha256-verified) and any quantized
    encoding is undone. Returns ``(version, tree)`` for the newest
    version, or the one requested."""
    store = _attach_durable(root)
    index = _durable_index(store, name)
    if not index:
        raise FileNotFoundError(
            f"no durable weight versions under {root!r}"
            + (f" for store {name!r}" if name else ""))
    if version is None:
        version = max(index)
    cid = index.get(int(version))
    if cid is None:
        raise KeyError(f"no durable manifest for version {version} under "
                       f"{root!r} (have {sorted(index)})")
    man = store.read(cid)
    spec = _spec_from_payload(man.spec)
    meta = man.stats["chunks"]
    key_hash: Dict[str, str] = {}
    sizes: Dict[str, int] = {}
    for key, entry in man.leaves.items():
        h, n = next(iter(entry.chunks.values()))
        key_hash[key] = h
        sizes[h] = n
    fetch = getattr(store, "fetch_chunks", None)
    if fetch is not None:
        blobs = fetch(sizes)
    else:
        from ray_tpu.ckpt import manifest as mf

        blobs = {h: mf.read_chunk(store.root, h) for h in sizes}
    by_leaf: Dict[str, List[Tuple[Box, np.ndarray]]] = {}
    for key, h in key_hash.items():
        leaf, box = _split_key(key)
        info = meta[key]
        arr = np.frombuffer(blobs[h], dtype=np.dtype(info["dtype"]))
        arr = arr.reshape(tuple(info["shape"]))
        arr = _decode_chunk(arr, {"enc": info.get("enc")})
        by_leaf.setdefault(leaf, []).append((box, arr))
    leaves: Dict[str, np.ndarray] = {}
    for leaf, (shape, dtype) in spec.meta.items():
        out = np.empty(shape, dtype=np.dtype(dtype))
        for box, arr in by_leaf.get(leaf, ()):
            out[box_slices(box)] = np.asarray(arr).reshape(
                tuple(b - a for a, b in box))
        leaves[leaf] = out
    return int(version), unflatten_tree(man.skeleton, leaves)

"""Transport tier: lower a TransferPlan onto real data movement.

Three tiers, picked by topology (mirrors the collective backends —
SURVEY.md's CPU/ICI split):

- **Object plane** (:func:`publish_host_shards` / ``WeightStore.pull_shards``):
  the general cross-mesh path. Each source host cuts exactly the plan's
  intersection chunks out of its resident shards and publishes them through
  the store; destination hosts pull only the chunks overlapping their boxes.
  Owner-tracked refs ride the normal object plane (chunked, spillable,
  location-directed) — no host ever sees a gathered array.

- **Collective tier** (:func:`collective_reshard`): when src and dst are the
  SAME mesh (same hosts), edges lower to p2p over the group's eager tier
  (``collective/collective_group.py`` send/recv — store-rendezvous on CPU,
  device-resident pulls on the XLA tier) and the store is never touched.

- **XLA tier** (:func:`jax_reshard`): single-controller over live jax
  devices — resharding is one ``jax.device_put`` to the new
  ``NamedSharding``; XLA emits the ICI collective exchange (the
  "portable collective communication" lowering of PAPERS.md). Used by
  in-process mesh owners (e.g. an engine swapping to a new layout).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.weights.plan import (
    RedistributionProgram,
    TransferPlan,
    maybe_lower_collective,
    note_lowering_fallback,
    plan_reshard,
)
from ray_tpu.weights.spec import (
    Box,
    ShardedTreeSpec,
    box_slices,
    flatten_tree,
    host_boxes,
    rel_slices,
    unflatten_tree,
    unique_boxes,
)
from ray_tpu.weights.store import WeightStore, _chunk_key


def local_shards_of(tree: Any, spec: ShardedTreeSpec, host: str
                    ) -> Dict[str, Dict[Box, np.ndarray]]:
    """Cut ``host``'s resident shards out of a locally-held full tree.
    Test/bootstrap convenience — in SPMD deployments each host already holds
    only its shards and passes them directly."""
    _, leaves = flatten_tree(tree)
    out: Dict[str, Dict[Box, np.ndarray]] = {}
    for leaf, value in leaves.items():
        arr = np.asarray(value)
        shape, _ = spec.meta[leaf]
        out[leaf] = {box: arr[box_slices(box)]
                     for box in host_boxes(spec.mesh, spec.part_of(leaf),
                                           arr.shape, host)}
    return out


def _cut(chunk_box: Box, src_box: Box, shard: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(shard[rel_slices(chunk_box, src_box)])


def publish_host_shards(store: WeightStore, version: int,
                        spec: ShardedTreeSpec, host: str,
                        shards: Dict[str, Dict[Box, np.ndarray]],
                        *, skeleton: Any = None,
                        dst_spec: Optional[ShardedTreeSpec] = None,
                        durable: bool = False,
                        timeout: float = 300.0,
                        delta_from: Optional[int] = None,
                        compression=None) -> int:
    """One source host's side of a mesh publish.

    Every host of ``spec.mesh`` calls this with the same ``version``; the
    store commits once all expected chunks arrive. With ``dst_spec`` the
    plan's exact intersection chunks are published (minimal bytes for a
    known destination); without it, the host's unique shard boxes are
    published as-is (subscriber-agnostic; consumers slice on pull).

    ``delta_from``/``compression`` behave as in :meth:`WeightStore.publish`
    (each host deltas its own chunk set against the base manifest;
    quantized encodings land per chunk in the shared manifest).

    Returns the number of chunks this host contributed.
    """
    if skeleton is None:
        skeleton = {leaf: leaf for leaf in sorted(spec.meta)}
    if dst_spec is not None:
        plan = plan_reshard(spec, dst_spec)
        mine: Dict[str, np.ndarray] = {}
        for e in plan.sends_from(host):
            key = _chunk_key(e.leaf, e.box)
            if key in mine:
                continue
            mine[key] = _cut(e.box, e.src_box, shards[e.leaf][e.src_box])
        # chunk count must be identical on every publisher: the full set of
        # distinct non-local chunk keys, plus local-only chunks a dst host
        # already holds (those never cross, so they are NOT published;
        # consumers satisfy them from their own shards)
        expected = len({e.chunk_key() for e in plan.edges if not e.local})
    else:
        mine = {}
        for leaf, boxes in shards.items():
            grid = unique_boxes(spec.mesh, spec.part_of(leaf),
                                spec.meta[leaf][0])
            for box, arr in boxes.items():
                # first replica holder publishes; others stand down
                if grid.get(box, (host,))[0] != host:
                    continue
                mine[_chunk_key(leaf, box)] = np.ascontiguousarray(arr)
        expected = sum(len(unique_boxes(spec.mesh, spec.part_of(leaf),
                                        spec.meta[leaf][0]))
                       for leaf in spec.meta)
    store._publish_chunks(version, skeleton, spec, mine,
                          num_chunks=expected, durable=durable,
                          timeout=timeout, delta_from=delta_from,
                          compression=compression)
    return len(mine)


def pull_with_locals(store: WeightStore, version: Optional[int],
                     src_spec: ShardedTreeSpec, dst_spec: ShardedTreeSpec,
                     host: str,
                     local: Dict[str, Dict[Box, np.ndarray]],
                     timeout: float = 300.0
                     ) -> Dict[str, Dict[Box, np.ndarray]]:
    """Destination-side assembly when this host is ALSO a source host (a
    same-cluster reshard): plan-local chunks are copied from ``local``
    shards, only the rest is pulled from the store."""
    plan = plan_reshard(src_spec, dst_spec)
    pulled = store.pull_shards(dst_spec, host, version, timeout=timeout)
    for e in plan.locals_on(host):
        shard = pulled[e.leaf][e.dst_box]
        shard[rel_slices(e.box, e.dst_box)] = \
            local[e.leaf][e.src_box][rel_slices(e.box, e.src_box)]
    return pulled


# ---------------------------------------------------------------------------
# Collective tier: same-mesh reshard without touching the store
# ---------------------------------------------------------------------------


def _alloc_dst(plan: TransferPlan, host: str
               ) -> Dict[str, Dict[Box, np.ndarray]]:
    out: Dict[str, Dict[Box, np.ndarray]] = {}
    for leaf, (shape, dtype) in plan.dst.meta.items():
        out[leaf] = {
            dbox: np.empty(tuple(b - a for a, b in dbox),
                           dtype=np.dtype(dtype))
            for dbox in host_boxes(plan.dst.mesh, plan.dst.part_of(leaf),
                                   shape, host)}
    return out


def _fill_locals(plan: TransferPlan, host: str,
                 shards: Dict[str, Dict[Box, np.ndarray]],
                 out: Dict[str, Dict[Box, np.ndarray]]) -> None:
    for e in plan.edges:
        if e.local and e.dst_host == host:
            out[e.leaf][e.dst_box][rel_slices(e.box, e.dst_box)] = \
                shards[e.leaf][e.src_box][rel_slices(e.box, e.src_box)]


def redistribute(program: RedistributionProgram, group, host: str,
                 shards: Dict[str, Dict[Box, np.ndarray]],
                 ) -> Dict[str, Dict[Box, np.ndarray]]:
    """Execute a lowered :class:`RedistributionProgram` over an initialized
    collective group whose rank i is host i of BOTH meshes (src and dst
    host sets must coincide — validated before any byte moves). The
    program's rounds bound each host's in-flight bytes: within a round a
    host posts its sends then drains its recvs, and a group barrier
    between rounds keeps every host in lock-step — without it, a host
    whose recv edges all pack into late rounds would race ahead and post
    its entire send set eagerly, which is exactly the unbounded behavior
    the program exists to kill. A trailing barrier fences call N from
    call N+1 on the same group: tags are global edge indices, reused
    verbatim by the next reshard, and the eager p2p tier OVERWRITES an
    unconsumed slot — without the fence a fast host's next-epoch send
    could clobber a message a slow peer has not drained yet.

    Deterministic pairing: the global edge index is the p2p tag, so the
    round structure can change without perturbing sender/receiver match-up.
    """
    plan = program.plan
    if tuple(plan.dst.mesh.hosts) != tuple(plan.src.mesh.hosts):
        raise ValueError(
            "redistribute needs identical src/dst host sets (rank i is "
            "host i of both meshes); use the object-plane transport for "
            "cross-mesh moves")
    rank_of = {h: i for i, h in enumerate(plan.src.mesh.hosts)}
    me = rank_of[host]
    out = _alloc_dst(plan, host)
    _fill_locals(plan, host, shards, out)
    for i, rnd in enumerate(program.rounds):
        if i:
            group.barrier()
        for tag in rnd:
            e = plan.edges[tag]
            if rank_of[e.src_host] != me:
                continue
            chunk = _cut(e.box, e.src_box, shards[e.leaf][e.src_box])
            group.send(chunk, rank_of[e.dst_host], tag=tag)
        for tag in rnd:
            e = plan.edges[tag]
            if e.dst_host != host:
                continue
            chunk = np.asarray(group.recv(rank_of[e.src_host], tag=tag))
            out[e.leaf][e.dst_box][rel_slices(e.box, e.dst_box)] = \
                chunk.reshape(tuple(b - a for a, b in e.box))
    if program.rounds:
        group.barrier()  # epoch fence: tags are reusable after this
    return out


def collective_reshard(plan: TransferPlan, group, host: str,
                       shards: Dict[str, Dict[Box, np.ndarray]],
                       program: Optional[RedistributionProgram] = None,
                       ) -> Dict[str, Dict[Box, np.ndarray]]:
    """Execute ``plan`` over an initialized collective group whose rank i is
    host i of BOTH meshes (src and dst hosts must coincide — the
    same-mesh/live-reshard case). Edges lower to the group's eager p2p tier;
    on the XLA backend the payload stays device-resident at the sender until
    the receiver pulls it (no store, no driver relay).

    The plan is lowered to a :class:`RedistributionProgram` first (pass a
    pre-computed ``program`` lowered from this SAME plan to share one
    lowering across the gang) — ``no_gather()`` is asserted before any
    byte moves and the rounds bound in-flight bytes. A plan that cannot
    be lowered falls back to a single unbounded round (everything posted,
    then drained) with a rate-limited warning, never silently.
    """
    import time as _time

    from ray_tpu.util import tracing
    from ray_tpu.weights.store import _obs

    src_hosts = plan.src.mesh.hosts
    if tuple(plan.dst.mesh.hosts) != tuple(src_hosts):
        raise ValueError(
            "collective_reshard needs identical src/dst host sets; use the "
            "object-plane transport for cross-mesh moves")
    if program is not None and program.plan is not plan:
        raise ValueError(
            "collective_reshard: the pre-computed program was lowered from "
            "a DIFFERENT plan — executing it would move the stale plan's "
            "geometry; re-lower with lower_collective(plan)")
    if program is None:
        program = maybe_lower_collective(plan)  # logs on fallback
        if program is None:
            # plan refuses no-gather lowering (logged above): execute as
            # one unbounded round — all sends posted, then drained
            tags = [i for i, e in enumerate(plan.edges) if not e.local]
            program = RedistributionProgram(plan=plan,
                                            rounds=[tags] if tags else [])
    t0 = _time.perf_counter()
    with tracing.profile("weights.reshard", category="weights", host=host):
        out = redistribute(program, group, host, shards)
    _obs()["reshard"].observe(_time.perf_counter() - t0)
    return out


# ---------------------------------------------------------------------------
# XLA tier: in-process device reshard
# ---------------------------------------------------------------------------

# per-leaf outcome counters for the device-tier reshard path. "lowered" =
# the explicit shard-assembly redistribution ran; "fallback" = a sharded
# jax.Array went through bare jax.device_put cross-sharding — the path
# that can trigger XLA's "involuntary full rematerialization" warning
# (MULTICHIP_r05). fallback must stay 0 on addressable meshes; tests
# regression-assert it.
_lower_lock = threading.Lock()
_lower_counts = {"lowered": 0, "noop": 0, "host_put": 0, "fallback": 0}


def reshard_lowering_stats() -> Dict[str, int]:
    with _lower_lock:
        return dict(_lower_counts)


def reset_reshard_lowering_stats() -> None:
    with _lower_lock:
        for k in _lower_counts:
            _lower_counts[k] = 0


def _count(outcome: str) -> None:
    with _lower_lock:
        _lower_counts[outcome] += 1


def _norm_box(idx: Tuple, shape: Tuple[int, ...]) -> Box:
    """A devices_indices_map entry (tuple of slices) as a global-coords
    box."""
    box = []
    for sl, dim in zip(idx, shape):
        start, stop, _ = sl.indices(dim)
        box.append((start, stop))
    return tuple(box)


def _assemble_device_shards(jax, leaf, dst_sharding):
    """The portable-redistribution lowering of a device-tier sharding
    transition (PAPERS.md, arxiv 2112.01075): build each destination
    device's shard by copying exactly the intersecting slices out of the
    source array's resident per-device shards, then bind them with
    ``make_array_from_single_device_arrays``. XLA's resharding machinery
    (and its replicate-then-slice "involuntary full rematerialization"
    fallback) never runs; no buffer larger than one destination shard is
    created unless the destination declares replication."""
    from ray_tpu.weights.spec import intersect_box, rel_slices

    shape = tuple(leaf.shape)
    dst_map = dst_sharding.addressable_devices_indices_map(shape)
    # dedupe replicated source shards by box BEFORE the D2H copy: a leaf
    # replicated over N devices has N identical shards, and materializing
    # (then overwrite-filling from) each one would multiply host traffic N×
    src_by_box: Dict[Box, Any] = {}
    for s in leaf.addressable_shards:
        src_by_box.setdefault(_norm_box(s.index, shape), s.data)
    src_pieces = [(box, np.asarray(data))
                  for box, data in src_by_box.items()]
    dtype = src_pieces[0][1].dtype if src_pieces else np.asarray(leaf).dtype
    bufs = []
    for dev, idx in dst_map.items():
        dbox = _norm_box(idx, shape)
        buf = np.empty(tuple(b - a for a, b in dbox), dtype=dtype)
        for sbox, sdata in src_pieces:
            inter = intersect_box(dbox, sbox)
            if inter is None:
                continue
            buf[rel_slices(inter, dbox)] = sdata[rel_slices(inter, sbox)]
        bufs.append(jax.device_put(buf, dev))
    return jax.make_array_from_single_device_arrays(shape, dst_sharding,
                                                    bufs)


def _reshard_leaf(jax, leaf: Any, dst_sharding) -> Any:
    """One leaf onto ``dst_sharding`` without XLA rematerialization.

    Host values upload with a plain device_put (no transition exists);
    device arrays already laid out right pass through; every other
    addressable transition takes the explicit no-gather assembly. The
    bare cross-sharding device_put remains only for non-addressable
    arrays (multi-controller handoff) — counted and logged, never
    silent."""
    if not isinstance(leaf, jax.Array):
        _count("host_put")
        return jax.device_put(leaf, dst_sharding)
    try:
        if leaf.sharding.is_equivalent_to(dst_sharding, len(leaf.shape)):
            _count("noop")
            return leaf
    except Exception:
        pass
    if leaf.is_fully_addressable:
        arr = _assemble_device_shards(jax, leaf, dst_sharding)
        _count("lowered")
        return arr
    _count("fallback")
    note_lowering_fallback(
        "device_put_cross_sharding",
        f"non-addressable array {leaf.shape} -> {dst_sharding}; XLA may "
        f"rematerialize")
    return jax.device_put(leaf, dst_sharding)


def jax_reshard(tree: Any, mesh_axes: Dict[str, int],
                parts: Dict[str, Tuple[Optional[str], ...]],
                default_part: Tuple[Optional[str], ...] = ()) -> Any:
    """Reshard a pytree onto the live local device mesh. ``mesh_axes`` is
    name->size over ``jax.devices()``.

    Sharding *transitions* (a live ``jax.Array`` moving to a different
    layout) lower to the explicit per-shard redistribution of
    :func:`_assemble_device_shards` instead of a bare cross-sharding
    ``jax.device_put`` — killing the XLA replicate-then-slice
    rematerialization fallback MULTICHIP_r05 kept logging. Host arrays
    still upload directly (there is nothing to rematerialize)."""
    import time as _time

    from ray_tpu.util import tracing
    from ray_tpu.utils import import_jax
    from ray_tpu.weights.store import _obs

    jax = import_jax()
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    t0 = _time.perf_counter()
    with tracing.profile("weights.reshard", category="weights"):
        names = tuple(mesh_axes)
        shape = tuple(mesh_axes[n] for n in names)
        devices = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
        mesh = Mesh(devices, names)
        skeleton, leaves = flatten_tree(tree)
        out = {}
        for path, leaf in leaves.items():
            part = parts.get(path, default_part)
            pspec = PartitionSpec(*part) if part else PartitionSpec()
            out[path] = _reshard_leaf(jax, leaf, NamedSharding(mesh, pspec))
        result = unflatten_tree(skeleton, out)
    _obs()["reshard"].observe(_time.perf_counter() - t0)
    return result

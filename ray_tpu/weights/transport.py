"""Transport tier: lower a TransferPlan onto real data movement.

Three tiers, picked by topology (mirrors the collective backends —
SURVEY.md's CPU/ICI split):

- **Object plane** (:func:`publish_host_shards` / ``WeightStore.pull_shards``):
  the general cross-mesh path. Each source host cuts exactly the plan's
  intersection chunks out of its resident shards and publishes them through
  the store; destination hosts pull only the chunks overlapping their boxes.
  Owner-tracked refs ride the normal object plane (chunked, spillable,
  location-directed) — no host ever sees a gathered array.

- **Collective tier** (:func:`collective_reshard`): when src and dst are the
  SAME mesh (same hosts), edges lower to p2p over the group's eager tier
  (``collective/collective_group.py`` send/recv — store-rendezvous on CPU,
  device-resident pulls on the XLA tier) and the store is never touched.

- **XLA tier** (:func:`jax_reshard`): single-controller over live jax
  devices — resharding is one ``jax.device_put`` to the new
  ``NamedSharding``; XLA emits the ICI collective exchange (the
  "portable collective communication" lowering of PAPERS.md). Used by
  in-process mesh owners (e.g. an engine swapping to a new layout).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.weights.plan import TransferPlan, plan_reshard
from ray_tpu.weights.spec import (
    Box,
    ShardedTreeSpec,
    box_slices,
    flatten_tree,
    host_boxes,
    rel_slices,
    unflatten_tree,
    unique_boxes,
)
from ray_tpu.weights.store import WeightStore, _chunk_key


def local_shards_of(tree: Any, spec: ShardedTreeSpec, host: str
                    ) -> Dict[str, Dict[Box, np.ndarray]]:
    """Cut ``host``'s resident shards out of a locally-held full tree.
    Test/bootstrap convenience — in SPMD deployments each host already holds
    only its shards and passes them directly."""
    _, leaves = flatten_tree(tree)
    out: Dict[str, Dict[Box, np.ndarray]] = {}
    for leaf, value in leaves.items():
        arr = np.asarray(value)
        shape, _ = spec.meta[leaf]
        out[leaf] = {box: arr[box_slices(box)]
                     for box in host_boxes(spec.mesh, spec.part_of(leaf),
                                           arr.shape, host)}
    return out


def _cut(chunk_box: Box, src_box: Box, shard: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(shard[rel_slices(chunk_box, src_box)])


def publish_host_shards(store: WeightStore, version: int,
                        spec: ShardedTreeSpec, host: str,
                        shards: Dict[str, Dict[Box, np.ndarray]],
                        *, skeleton: Any = None,
                        dst_spec: Optional[ShardedTreeSpec] = None,
                        durable: bool = False,
                        timeout: float = 300.0) -> int:
    """One source host's side of a mesh publish.

    Every host of ``spec.mesh`` calls this with the same ``version``; the
    store commits once all expected chunks arrive. With ``dst_spec`` the
    plan's exact intersection chunks are published (minimal bytes for a
    known destination); without it, the host's unique shard boxes are
    published as-is (subscriber-agnostic; consumers slice on pull).

    Returns the number of chunks this host contributed.
    """
    if skeleton is None:
        skeleton = {leaf: leaf for leaf in sorted(spec.meta)}
    if dst_spec is not None:
        plan = plan_reshard(spec, dst_spec)
        mine: Dict[str, np.ndarray] = {}
        for e in plan.sends_from(host):
            key = _chunk_key(e.leaf, e.box)
            if key in mine:
                continue
            mine[key] = _cut(e.box, e.src_box, shards[e.leaf][e.src_box])
        # chunk count must be identical on every publisher: the full set of
        # distinct non-local chunk keys, plus local-only chunks a dst host
        # already holds (those never cross, so they are NOT published;
        # consumers satisfy them from their own shards)
        expected = len({e.chunk_key() for e in plan.edges if not e.local})
    else:
        mine = {}
        for leaf, boxes in shards.items():
            grid = unique_boxes(spec.mesh, spec.part_of(leaf),
                                spec.meta[leaf][0])
            for box, arr in boxes.items():
                # first replica holder publishes; others stand down
                if grid.get(box, (host,))[0] != host:
                    continue
                mine[_chunk_key(leaf, box)] = np.ascontiguousarray(arr)
        expected = sum(len(unique_boxes(spec.mesh, spec.part_of(leaf),
                                        spec.meta[leaf][0]))
                       for leaf in spec.meta)
    store._publish_chunks(version, skeleton, spec, mine,
                          num_chunks=expected, durable=durable,
                          timeout=timeout)
    return len(mine)


def pull_with_locals(store: WeightStore, version: Optional[int],
                     src_spec: ShardedTreeSpec, dst_spec: ShardedTreeSpec,
                     host: str,
                     local: Dict[str, Dict[Box, np.ndarray]],
                     timeout: float = 300.0
                     ) -> Dict[str, Dict[Box, np.ndarray]]:
    """Destination-side assembly when this host is ALSO a source host (a
    same-cluster reshard): plan-local chunks are copied from ``local``
    shards, only the rest is pulled from the store."""
    plan = plan_reshard(src_spec, dst_spec)
    pulled = store.pull_shards(dst_spec, host, version, timeout=timeout)
    for e in plan.locals_on(host):
        shard = pulled[e.leaf][e.dst_box]
        shard[rel_slices(e.box, e.dst_box)] = \
            local[e.leaf][e.src_box][rel_slices(e.box, e.src_box)]
    return pulled


# ---------------------------------------------------------------------------
# Collective tier: same-mesh reshard without touching the store
# ---------------------------------------------------------------------------


def collective_reshard(plan: TransferPlan, group, host: str,
                       shards: Dict[str, Dict[Box, np.ndarray]],
                       ) -> Dict[str, Dict[Box, np.ndarray]]:
    """Execute ``plan`` over an initialized collective group whose rank i is
    host i of BOTH meshes (src and dst hosts must coincide — the
    same-mesh/live-reshard case). Edges lower to the group's eager p2p tier;
    on the XLA backend the payload stays device-resident at the sender until
    the receiver pulls it (no store, no driver relay).

    Deterministic pairing: edges are processed in plan order with the edge
    index as the p2p tag; every host posts all its sends, then drains its
    recvs — the CPU store tier parks receivers without spinning, the XLA
    tier leaves tensors parked in the sender's device store.
    """
    import time as _time

    from ray_tpu.util import tracing
    from ray_tpu.weights.store import _obs

    src_hosts = plan.src.mesh.hosts
    if tuple(plan.dst.mesh.hosts) != tuple(src_hosts):
        raise ValueError(
            "collective_reshard needs identical src/dst host sets; use the "
            "object-plane transport for cross-mesh moves")
    t0 = _time.perf_counter()
    with tracing.profile("weights.reshard", category="weights", host=host):
        rank_of = {h: i for i, h in enumerate(src_hosts)}
        me = rank_of[host]
        for tag, e in enumerate(plan.edges):
            if e.local or rank_of[e.src_host] != me:
                continue
            chunk = _cut(e.box, e.src_box, shards[e.leaf][e.src_box])
            group.send(chunk, rank_of[e.dst_host], tag=tag)
        out: Dict[str, Dict[Box, np.ndarray]] = {}
        for leaf, (shape, dtype) in plan.dst.meta.items():
            out[leaf] = {
                dbox: np.empty(tuple(b - a for a, b in dbox),
                               dtype=np.dtype(dtype))
                for dbox in host_boxes(plan.dst.mesh, plan.dst.part_of(leaf),
                                       shape, host)}
        for tag, e in enumerate(plan.edges):
            if e.dst_host != host:
                continue
            dst = out[e.leaf][e.dst_box]
            if e.local:
                dst[rel_slices(e.box, e.dst_box)] = \
                    shards[e.leaf][e.src_box][rel_slices(e.box, e.src_box)]
            else:
                chunk = np.asarray(group.recv(rank_of[e.src_host], tag=tag))
                dst[rel_slices(e.box, e.dst_box)] = chunk.reshape(
                    tuple(b - a for a, b in e.box))
    _obs()["reshard"].observe(_time.perf_counter() - t0)
    return out


# ---------------------------------------------------------------------------
# XLA tier: in-process device reshard
# ---------------------------------------------------------------------------


def jax_reshard(tree: Any, mesh_axes: Dict[str, int],
                parts: Dict[str, Tuple[Optional[str], ...]],
                default_part: Tuple[Optional[str], ...] = ()) -> Any:
    """Reshard a pytree onto the live local device mesh via one
    ``jax.device_put`` per leaf — XLA plans the collective exchange
    (the ICI lowering; on the CPU test tier this runs over the 8-device
    virtual mesh). ``mesh_axes`` is name->size over ``jax.devices()``."""
    import time as _time

    from ray_tpu.util import tracing
    from ray_tpu.utils import import_jax
    from ray_tpu.weights.store import _obs

    jax = import_jax()
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    t0 = _time.perf_counter()
    with tracing.profile("weights.reshard", category="weights"):
        names = tuple(mesh_axes)
        shape = tuple(mesh_axes[n] for n in names)
        devices = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
        mesh = Mesh(devices, names)
        skeleton, leaves = flatten_tree(tree)
        out = {}
        for path, leaf in leaves.items():
            part = parts.get(path, default_part)
            pspec = PartitionSpec(*part) if part else PartitionSpec()
            out[path] = jax.device_put(leaf, NamedSharding(mesh, pspec))
        result = unflatten_tree(skeleton, out)
    _obs()["reshard"].observe(_time.perf_counter() - t0)
    return result

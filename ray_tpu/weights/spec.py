"""Mesh / sharding specs for the weight plane — hardware-free by design.

Reference: jax.sharding (``Mesh`` + ``PartitionSpec`` + the
``devices_indices_map`` a ``NamedSharding`` induces) and the array-
redistribution formulation of "Memory-efficient array redistribution through
portable collective communication" (PAPERS.md): a resharding is fully
described by (src mesh, src partition, dst mesh, dst partition) and lowers to
a set of shard-slice exchanges. The planner (``plan.py``) needs only the
*index geometry* of both sides, never live devices — so a serve replica set
with no TPU at all can be a destination "mesh", and plans can be computed
(and unit-tested) on any host.

Conventions:

- Devices of a mesh are numbered row-major over ``shape``; they are split
  contiguously across ``hosts`` (``jax.Mesh`` over a pod slice does the
  same: earlier devices on earlier hosts).
- A leaf's partition is a per-dimension tuple of mesh axis names (or None
  for replicated dims) — exactly ``jax.sharding.PartitionSpec`` restricted
  to one axis per dim.
- Boxes are tuples of ``(start, stop)`` pairs in GLOBAL array coordinates.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

Box = Tuple[Tuple[int, int], ...]  # ((start, stop), ...) per dim


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


@dataclass(frozen=True)
class MeshSpec:
    """Abstract device mesh: named axes over row-major devices, split
    contiguously across hosts (wire-registered; see wire.py)."""

    shape: Tuple[int, ...]
    axis_names: Tuple[str, ...]
    hosts: Tuple[str, ...]

    def __post_init__(self):
        shape = tuple(int(s) for s in self.shape)
        names = tuple(self.axis_names)
        hosts = tuple(self.hosts)
        object.__setattr__(self, "shape", shape)
        object.__setattr__(self, "axis_names", names)
        object.__setattr__(self, "hosts", hosts)
        if len(shape) != len(names):
            raise ValueError(f"mesh shape {shape} vs axis_names {names}")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate mesh axis names: {names}")
        if not hosts:
            raise ValueError("mesh needs at least one host")
        if self.size % len(hosts) != 0:
            raise ValueError(
                f"{self.size} devices do not split evenly over "
                f"{len(hosts)} hosts")

    @property
    def size(self) -> int:
        return _prod(self.shape)

    @property
    def devices_per_host(self) -> int:
        return self.size // len(self.hosts)

    def axis_size(self, name: str) -> int:
        return self.shape[self.axis_names.index(name)]

    def host_of(self, device: int) -> str:
        return self.hosts[device // self.devices_per_host]

    def host_rank(self, host: str) -> int:
        return self.hosts.index(host)

    def device_coords(self, device: int) -> Tuple[int, ...]:
        coords = []
        rem = device
        for s in reversed(self.shape):
            coords.append(rem % s)
            rem //= s
        return tuple(reversed(coords))

    @classmethod
    def host_mesh(cls, hosts, axis: str = "hosts") -> "MeshSpec":
        """1-D mesh with one device per host (serve replica sets, learner
        broadcast groups — any destination that is just N processes)."""
        hosts = tuple(hosts)
        return cls(shape=(len(hosts),), axis_names=(axis,), hosts=hosts)


def shard_box(mesh: MeshSpec, part: Tuple[Optional[str], ...],
              shape: Tuple[int, ...], device: int) -> Box:
    """The global-coordinate box of ``device``'s shard of an array."""
    if len(part) > len(shape):
        raise ValueError(f"partition {part} longer than array shape {shape}")
    coords = mesh.device_coords(device)
    box: List[Tuple[int, int]] = []
    for i, dim in enumerate(shape):
        axis = part[i] if i < len(part) else None
        if axis is None:
            box.append((0, dim))
            continue
        n = mesh.axis_size(axis)
        if dim % n != 0:
            raise ValueError(
                f"dim {i} ({dim}) not divisible by mesh axis "
                f"{axis!r} ({n})")
        chunk = dim // n
        c = coords[mesh.axis_names.index(axis)]
        box.append((c * chunk, (c + 1) * chunk))
    return tuple(box)


def unique_boxes(mesh: MeshSpec, part: Tuple[Optional[str], ...],
                 shape: Tuple[int, ...]) -> Dict[Box, Tuple[str, ...]]:
    """box -> hosts holding a replica of it (deduped, host order)."""
    out: Dict[Box, List[str]] = {}
    for d in range(mesh.size):
        box = shard_box(mesh, part, shape, d)
        holders = out.setdefault(box, [])
        h = mesh.host_of(d)
        if h not in holders:
            holders.append(h)
    return {b: tuple(hs) for b, hs in out.items()}


def host_boxes(mesh: MeshSpec, part: Tuple[Optional[str], ...],
               shape: Tuple[int, ...], host: str) -> Tuple[Box, ...]:
    """The distinct shard boxes resident on ``host`` (its devices' shards)."""
    per = mesh.devices_per_host
    rank = mesh.host_rank(host)
    seen: List[Box] = []
    for d in range(rank * per, (rank + 1) * per):
        box = shard_box(mesh, part, shape, d)
        if box not in seen:
            seen.append(box)
    return tuple(seen)


def box_nbytes(box: Box, itemsize: int) -> int:
    return _prod(stop - start for start, stop in box) * itemsize


def intersect_box(a: Box, b: Box) -> Optional[Box]:
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def box_slices(box: Box) -> Tuple[slice, ...]:
    return tuple(slice(start, stop) for start, stop in box)


def rel_slices(box: Box, within: Box) -> Tuple[slice, ...]:
    """``box`` as slices relative to the origin of ``within`` (for indexing
    into a shard held locally)."""
    return tuple(slice(b0 - w0, b1 - w0)
                 for (b0, b1), (w0, _) in zip(box, within))


# ---------------------------------------------------------------------------
# PyTrees: flatten to {path: leaf} + a rebuildable skeleton
# ---------------------------------------------------------------------------


def flatten_tree(tree: Any, _prefix: str = "") -> Tuple[Any, Dict[str, Any]]:
    """Flatten a nested dict/list/tuple pytree into (skeleton, leaves).

    The skeleton mirrors the nesting with each leaf replaced by its path
    string — it is wire-encodable (plain containers + strings) and
    ``unflatten_tree(skeleton, leaves)`` rebuilds the original structure.
    """
    leaves: Dict[str, Any] = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            return {k: walk(v, f"{prefix}{k}/") for k, v in sorted(node.items())}
        if isinstance(node, (list, tuple)):
            out = [walk(v, f"{prefix}{i}/") for i, v in enumerate(node)]
            if isinstance(node, list):
                return out
            if type(node) is not tuple and hasattr(node, "_fields"):
                # a namedtuple (optimizer states are trees of these):
                # remember the concrete class so unflatten can rebuild it
                # instead of degrading to a plain tuple
                cls = type(node)
                return ["__namedtuple__",
                        f"{cls.__module__}:{cls.__qualname__}"] + out
            return ["__tuple__"] + out
        path = prefix.rstrip("/") or "leaf"
        if path in leaves:
            raise ValueError(f"duplicate leaf path {path!r}")
        leaves[path] = node
        return path

    skeleton = walk(tree, _prefix)
    return skeleton, leaves


def unflatten_tree(skeleton: Any, leaves: Dict[str, Any]) -> Any:
    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            if node and node[0] == "__tuple__":
                return tuple(walk(v) for v in node[1:])
            if node and node[0] == "__namedtuple__":
                values = [walk(v) for v in node[2:]]
                try:
                    import importlib

                    mod, _, qual = node[1].partition(":")
                    cls = importlib.import_module(mod)
                    for part in qual.split("."):
                        cls = getattr(cls, part)
                    return cls(*values)
                except Exception:
                    return tuple(values)  # class gone: degrade gracefully
            return [walk(v) for v in node]
        return leaves[node]

    return walk(skeleton)


# ---------------------------------------------------------------------------
# Sharded tree spec: one side of a reshard
# ---------------------------------------------------------------------------


@dataclass
class ShardedTreeSpec:
    """Which mesh holds the tree, how each leaf is partitioned, and every
    leaf's (shape, dtype) — everything the planner needs about one side."""

    mesh: MeshSpec
    parts: Dict[str, Tuple[Optional[str], ...]]  # leaf path -> partition
    meta: Dict[str, Tuple[Tuple[int, ...], str]]  # path -> (shape, dtype str)

    def part_of(self, path: str) -> Tuple[Optional[str], ...]:
        return tuple(self.parts.get(path, ()))

    def leaf_nbytes(self, path: str) -> int:
        import numpy as np

        shape, dtype = self.meta[path]
        return _prod(shape) * np.dtype(dtype).itemsize

    @classmethod
    def from_tree(cls, tree: Any, mesh: MeshSpec,
                  parts: Optional[Dict[str, Tuple[Optional[str], ...]]] = None,
                  default_part: Tuple[Optional[str], ...] = (),
                  ) -> "ShardedTreeSpec":
        """Spec for a tree of array-likes. ``parts`` maps leaf paths to
        partitions; unlisted leaves use ``default_part`` (default:
        fully replicated)."""
        import numpy as np

        _, leaves = flatten_tree(tree)
        meta = {}
        out_parts = {}
        for path, leaf in leaves.items():
            arr = np.asarray(leaf)
            meta[path] = (tuple(arr.shape), arr.dtype.str)
            out_parts[path] = tuple((parts or {}).get(path, default_part))
        return cls(mesh=mesh, parts=out_parts, meta=meta)

    @classmethod
    def replicated(cls, tree: Any, hosts) -> "ShardedTreeSpec":
        """Fully-replicated spec over one device per host — the broadcast
        destination shape (N env-runners, N serve replicas)."""
        return cls.from_tree(tree, MeshSpec.host_mesh(hosts))

    def total_unique_bytes(self) -> int:
        """Sum over leaves of unique (deduplicated) shard bytes."""
        import numpy as np

        total = 0
        for path, (shape, dtype) in self.meta.items():
            item = np.dtype(dtype).itemsize
            for box in unique_boxes(self.mesh, self.part_of(path), shape):
                total += box_nbytes(box, item)
        return total

"""Resharding planner: (src spec, dst spec) -> minimal shard-exchange plan.

Reference: "Memory-efficient array redistribution through portable
collective communication" (PAPERS.md) — a reshard is a set of slice
exchanges computed from the two index geometries; no step of the exchange
may materialize the full array on one participant. The planner works purely
on :mod:`ray_tpu.weights.spec` geometry:

- For every leaf, the distinct source shard boxes form a disjoint grid and
  so do the destination boxes; each (dst box ∩ src box) intersection becomes
  exactly ONE :class:`TransferEdge` per destination host that needs it.
- A destination host that already holds the bytes (it is also a source
  replica of the intersecting box) gets a ``local`` edge — zero bytes moved.
- When an intersection has several source replicas, the source host is
  chosen by a stable hash of the chunk key, so (a) fan-out spreads across
  replicas instead of hammering host 0 and (b) every destination of the
  same chunk pulls from the SAME source — the chunk is published once.

The resulting plan is transport-agnostic: ``transport.py`` lowers edges to
the collective tier (same mesh) or to chunked object-plane puts/pulls.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.weights.spec import (
    Box,
    MeshSpec,
    ShardedTreeSpec,
    box_nbytes,
    host_boxes,
    intersect_box,
    unique_boxes,
)


@dataclass(frozen=True)
class TransferEdge:
    """One chunk movement: ``box`` (global coords) of ``leaf`` travels from
    ``src_host`` to ``dst_host``. ``src_box`` is the source shard the chunk
    is cut from; ``dst_box`` the destination shard it lands in.
    (wire-registered; see wire.py)"""

    leaf: str
    src_host: str
    dst_host: str
    box: Box
    src_box: Box
    dst_box: Box
    nbytes: int
    local: bool

    def chunk_key(self) -> str:
        """Deterministic manifest key for this chunk's bytes. Keyed by leaf
        + box only: replicated destinations share one published chunk."""
        flat = ",".join(f"{a}:{b}" for a, b in self.box)
        return f"{self.leaf}|{flat}"


@dataclass
class TransferPlan:
    src: ShardedTreeSpec
    dst: ShardedTreeSpec
    edges: List[TransferEdge] = field(default_factory=list)

    # -- per-host views (what transports consume) --

    def sends_from(self, host: str) -> List[TransferEdge]:
        return [e for e in self.edges if e.src_host == host and not e.local]

    def recvs_to(self, host: str) -> List[TransferEdge]:
        return [e for e in self.edges if e.dst_host == host and not e.local]

    def locals_on(self, host: str) -> List[TransferEdge]:
        return [e for e in self.edges if e.dst_host == host and e.local]

    # -- stats / invariants --

    def bytes_moved(self) -> int:
        return sum(e.nbytes for e in self.edges if not e.local)

    def bytes_local(self) -> int:
        return sum(e.nbytes for e in self.edges if e.local)

    def unique_chunk_bytes(self) -> int:
        """Bytes published once per distinct chunk (replicated destinations
        share chunks)."""
        seen = {}
        for e in self.edges:
            if not e.local:
                seen[e.chunk_key()] = e.nbytes
        return sum(seen.values())

    def fanout(self) -> int:
        """Max destinations any single published chunk feeds."""
        counts: Dict[str, int] = {}
        for e in self.edges:
            if not e.local:
                counts[e.chunk_key()] = counts.get(e.chunk_key(), 0) + 1
        return max(counts.values(), default=0)

    def max_host_leaf_bytes(self, leaf: str) -> int:
        """The most bytes of ``leaf`` any single host holds at any point of
        the exchange: its resident source shards plus everything it
        receives. The no-gather property is
        ``max_host_leaf_bytes(leaf) < leaf_nbytes`` (unless a side
        legitimately replicates the leaf)."""
        import numpy as np

        shape, dtype = (self.src.meta.get(leaf) or self.dst.meta[leaf])
        item = np.dtype(dtype).itemsize
        held: Dict[str, int] = {}
        for host in set(self.src.mesh.hosts) | set(self.dst.mesh.hosts):
            total = 0
            if host in self.src.mesh.hosts:
                boxes = host_boxes(self.src.mesh, self.src.part_of(leaf),
                                   shape, host)
                total += sum(box_nbytes(b, item) for b in boxes)
            total += sum(e.nbytes for e in self.edges
                         if e.leaf == leaf and e.dst_host == host
                         and not e.local)
            held[host] = total
        return max(held.values(), default=0)

    def no_gather(self) -> bool:
        """True iff no host ever holds a full copy of any leaf that neither
        side declares replicated (a replicated side holds full copies by
        declaration — that is a broadcast, not a gather)."""
        import numpy as np

        for leaf, (shape, dtype) in self.dst.meta.items():
            full = box_nbytes(tuple((0, s) for s in shape),
                              np.dtype(dtype).itemsize)
            src_rep = all(a is None for a in self.src.part_of(leaf))
            dst_rep = all(a is None for a in self.dst.part_of(leaf))
            if src_rep or dst_rep:
                continue
            if self.max_host_leaf_bytes(leaf) >= full:
                return False
        return True

    def stats(self) -> Dict[str, float]:
        return {
            "num_edges": len(self.edges),
            "num_local_edges": sum(1 for e in self.edges if e.local),
            "bytes_moved": self.bytes_moved(),
            "bytes_local": self.bytes_local(),
            "unique_chunk_bytes": self.unique_chunk_bytes(),
            "fanout": self.fanout(),
            "num_leaves": len(self.dst.meta),
            "src_hosts": len(self.src.mesh.hosts),
            "dst_hosts": len(self.dst.mesh.hosts),
        }


def plan_reshard(src: ShardedTreeSpec, dst: ShardedTreeSpec) -> TransferPlan:
    """Compute the shard-exchange plan from ``src`` to ``dst``.

    Guarantees, by construction:

    - every destination shard's bytes arrive exactly once (the source boxes
      are a disjoint grid, so intersections tile each destination box);
    - total moved bytes <= sum of unique destination shard bytes;
    - no edge carries bytes its destination already holds (those become
      ``local`` edges).
    """
    import numpy as np

    if set(src.meta) != set(dst.meta):
        missing = set(src.meta) ^ set(dst.meta)
        raise ValueError(f"src/dst trees differ on leaves: {sorted(missing)}")
    import zlib

    plan = TransferPlan(src=src, dst=dst)
    for leaf in sorted(dst.meta):
        shape, dtype = dst.meta[leaf]
        if src.meta[leaf][0] != shape:
            raise ValueError(
                f"leaf {leaf!r} shape mismatch: src {src.meta[leaf][0]} vs "
                f"dst {shape}")
        item = np.dtype(dtype).itemsize
        src_grid = unique_boxes(src.mesh, src.part_of(leaf), shape)
        dst_grid = unique_boxes(dst.mesh, dst.part_of(leaf), shape)
        for dbox in sorted(dst_grid):
            for sbox in sorted(src_grid):
                inter = intersect_box(dbox, sbox)
                if inter is None:
                    continue
                nbytes = box_nbytes(inter, item)
                replicas = src_grid[sbox]
                for dhost in dst_grid[dbox]:
                    if dhost in replicas:
                        plan.edges.append(TransferEdge(
                            leaf=leaf, src_host=dhost, dst_host=dhost,
                            box=inter, src_box=sbox, dst_box=dbox,
                            nbytes=nbytes, local=True))
                        continue
                    flat = f"{leaf}|{inter}".encode()
                    shost = replicas[zlib.crc32(flat) % len(replicas)]
                    plan.edges.append(TransferEdge(
                        leaf=leaf, src_host=shost, dst_host=dhost,
                        box=inter, src_box=sbox, dst_box=dbox,
                        nbytes=nbytes, local=False))
    return plan

"""Resharding planner: (src spec, dst spec) -> minimal shard-exchange plan.

Reference: "Memory-efficient array redistribution through portable
collective communication" (PAPERS.md) — a reshard is a set of slice
exchanges computed from the two index geometries; no step of the exchange
may materialize the full array on one participant. The planner works purely
on :mod:`ray_tpu.weights.spec` geometry:

- For every leaf, the distinct source shard boxes form a disjoint grid and
  so do the destination boxes; each (dst box ∩ src box) intersection becomes
  exactly ONE :class:`TransferEdge` per destination host that needs it.
- A destination host that already holds the bytes (it is also a source
  replica of the intersecting box) gets a ``local`` edge — zero bytes moved.
- When an intersection has several source replicas, the source host is
  chosen by a stable hash of the chunk key, so (a) fan-out spreads across
  replicas instead of hammering host 0 and (b) every destination of the
  same chunk pulls from the SAME source — the chunk is published once.

The resulting plan is transport-agnostic: ``transport.py`` lowers edges to
the collective tier (same mesh) or to chunked object-plane puts/pulls.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.weights.spec import (
    Box,
    MeshSpec,
    ShardedTreeSpec,
    box_nbytes,
    host_boxes,
    intersect_box,
    unique_boxes,
)

logger = logging.getLogger("ray_tpu.weights")


@dataclass(frozen=True)
class TransferEdge:
    """One chunk movement: ``box`` (global coords) of ``leaf`` travels from
    ``src_host`` to ``dst_host``. ``src_box`` is the source shard the chunk
    is cut from; ``dst_box`` the destination shard it lands in.
    (wire-registered; see wire.py)"""

    leaf: str
    src_host: str
    dst_host: str
    box: Box
    src_box: Box
    dst_box: Box
    nbytes: int
    local: bool

    def chunk_key(self) -> str:
        """Deterministic manifest key for this chunk's bytes. Keyed by leaf
        + box only: replicated destinations share one published chunk."""
        flat = ",".join(f"{a}:{b}" for a, b in self.box)
        return f"{self.leaf}|{flat}"


@dataclass
class TransferPlan:
    src: ShardedTreeSpec
    dst: ShardedTreeSpec
    edges: List[TransferEdge] = field(default_factory=list)

    # -- per-host views (what transports consume) --

    def sends_from(self, host: str) -> List[TransferEdge]:
        return [e for e in self.edges if e.src_host == host and not e.local]

    def recvs_to(self, host: str) -> List[TransferEdge]:
        return [e for e in self.edges if e.dst_host == host and not e.local]

    def locals_on(self, host: str) -> List[TransferEdge]:
        return [e for e in self.edges if e.dst_host == host and e.local]

    # -- stats / invariants --

    def bytes_moved(self) -> int:
        return sum(e.nbytes for e in self.edges if not e.local)

    def bytes_local(self) -> int:
        return sum(e.nbytes for e in self.edges if e.local)

    def unique_chunk_bytes(self) -> int:
        """Bytes published once per distinct chunk (replicated destinations
        share chunks)."""
        seen = {}
        for e in self.edges:
            if not e.local:
                seen[e.chunk_key()] = e.nbytes
        return sum(seen.values())

    def fanout(self) -> int:
        """Max destinations any single published chunk feeds."""
        counts: Dict[str, int] = {}
        for e in self.edges:
            if not e.local:
                counts[e.chunk_key()] = counts.get(e.chunk_key(), 0) + 1
        return max(counts.values(), default=0)

    def max_host_leaf_bytes(self, leaf: str) -> int:
        """The most bytes of ``leaf`` any single host holds at any point of
        the exchange: its resident source shards plus everything it
        receives. The no-gather property is
        ``max_host_leaf_bytes(leaf) < leaf_nbytes`` (unless a side
        legitimately replicates the leaf)."""
        import numpy as np

        shape, dtype = (self.src.meta.get(leaf) or self.dst.meta[leaf])
        item = np.dtype(dtype).itemsize
        held: Dict[str, int] = {}
        for host in set(self.src.mesh.hosts) | set(self.dst.mesh.hosts):
            total = 0
            if host in self.src.mesh.hosts:
                boxes = host_boxes(self.src.mesh, self.src.part_of(leaf),
                                   shape, host)
                total += sum(box_nbytes(b, item) for b in boxes)
            total += sum(e.nbytes for e in self.edges
                         if e.leaf == leaf and e.dst_host == host
                         and not e.local)
            held[host] = total
        return max(held.values(), default=0)

    def no_gather(self) -> bool:
        """True iff no host ever holds a full copy of any leaf that neither
        side declares replicated (a replicated side holds full copies by
        declaration — that is a broadcast, not a gather)."""
        import numpy as np

        for leaf, (shape, dtype) in self.dst.meta.items():
            full = box_nbytes(tuple((0, s) for s in shape),
                              np.dtype(dtype).itemsize)
            src_rep = all(a is None for a in self.src.part_of(leaf))
            dst_rep = all(a is None for a in self.dst.part_of(leaf))
            if src_rep or dst_rep:
                continue
            if self.max_host_leaf_bytes(leaf) >= full:
                return False
        return True

    def stats(self) -> Dict[str, float]:
        return {
            "num_edges": len(self.edges),
            "num_local_edges": sum(1 for e in self.edges if e.local),
            "bytes_moved": self.bytes_moved(),
            "bytes_local": self.bytes_local(),
            "unique_chunk_bytes": self.unique_chunk_bytes(),
            "fanout": self.fanout(),
            "num_leaves": len(self.dst.meta),
            "src_hosts": len(self.src.mesh.hosts),
            "dst_hosts": len(self.dst.mesh.hosts),
        }


def plan_reshard(src: ShardedTreeSpec, dst: ShardedTreeSpec) -> TransferPlan:
    """Compute the shard-exchange plan from ``src`` to ``dst``.

    Guarantees, by construction:

    - every destination shard's bytes arrive exactly once (the source boxes
      are a disjoint grid, so intersections tile each destination box);
    - total moved bytes <= sum of unique destination shard bytes;
    - no edge carries bytes its destination already holds (those become
      ``local`` edges).
    """
    import numpy as np

    if set(src.meta) != set(dst.meta):
        missing = set(src.meta) ^ set(dst.meta)
        raise ValueError(f"src/dst trees differ on leaves: {sorted(missing)}")
    import zlib

    plan = TransferPlan(src=src, dst=dst)
    for leaf in sorted(dst.meta):
        shape, dtype = dst.meta[leaf]
        if src.meta[leaf][0] != shape:
            raise ValueError(
                f"leaf {leaf!r} shape mismatch: src {src.meta[leaf][0]} vs "
                f"dst {shape}")
        item = np.dtype(dtype).itemsize
        src_grid = unique_boxes(src.mesh, src.part_of(leaf), shape)
        dst_grid = unique_boxes(dst.mesh, dst.part_of(leaf), shape)
        for dbox in sorted(dst_grid):
            for sbox in sorted(src_grid):
                inter = intersect_box(dbox, sbox)
                if inter is None:
                    continue
                nbytes = box_nbytes(inter, item)
                replicas = src_grid[sbox]
                for dhost in dst_grid[dbox]:
                    if dhost in replicas:
                        plan.edges.append(TransferEdge(
                            leaf=leaf, src_host=dhost, dst_host=dhost,
                            box=inter, src_box=sbox, dst_box=dbox,
                            nbytes=nbytes, local=True))
                        continue
                    flat = f"{leaf}|{inter}".encode()
                    shost = replicas[zlib.crc32(flat) % len(replicas)]
                    plan.edges.append(TransferEdge(
                        leaf=leaf, src_host=shost, dst_host=dhost,
                        box=inter, src_box=sbox, dst_box=dbox,
                        nbytes=nbytes, local=False))
    return plan


# ---------------------------------------------------------------------------
# Collective lowering: plan -> redistribution program
#
# Reference: "Memory-efficient array redistribution through portable
# collective communication" (PAPERS.md, arxiv 2112.01075) — a sharding
# transition is a *program* of cheap point-to-point exchanges, never a
# replicate-then-slice (XLA's "involuntary full rematerialization"
# fallback). The lowering here turns a TransferPlan into ordered rounds of
# edges such that (a) the plan is proven no-gather BEFORE any byte moves
# and (b) no host's in-flight send+recv bytes in one round exceed a bound,
# so the peak working set stays a constant factor over the resident shards
# regardless of how adversarial the (src, dst) geometry pair is.
# ---------------------------------------------------------------------------


class ReshardLoweringError(ValueError):
    """The plan cannot be lowered to a no-gather collective program (some
    host would materialize a full non-replicated leaf)."""


@dataclass(frozen=True)
class DcnCostModel:
    """Two-tier bandwidth model for redistribution edges.

    Hosts mapping to the same node (``node_of``; default: every host its
    own node, i.e. everything is DCN) exchange over the fast tier (ICI /
    intra-slice); everything else crosses the data-center network. Costs
    are advisory — they order edges (long DCN transfers first, so they
    overlap the cheap intra-node ones) and price programs for the
    transport picker; they never change what bytes move.
    """

    ici_bytes_per_s: float = 40e9
    dcn_bytes_per_s: float = 3e9
    latency_s: float = 200e-6
    node_of: Optional[Callable[[str], str]] = None

    def _node(self, host: str) -> str:
        return self.node_of(host) if self.node_of is not None else host

    def is_dcn(self, edge: TransferEdge) -> bool:
        return self._node(edge.src_host) != self._node(edge.dst_host)

    def edge_seconds(self, edge: TransferEdge) -> float:
        if edge.local:
            return 0.0
        bw = self.dcn_bytes_per_s if self.is_dcn(edge) \
            else self.ici_bytes_per_s
        return self.latency_s + edge.nbytes / bw


@dataclass
class RedistributionProgram:
    """A lowered TransferPlan: ordered rounds of non-local edge indices.

    Within a round every sender posts its sends then drains its recvs; a
    host does not enter round ``r+1`` before finishing round ``r``, which
    is what bounds its in-flight bytes. The program is computed (and its
    invariants assertable) before any data movement."""

    plan: TransferPlan
    rounds: List[List[int]] = field(default_factory=list)
    est_seconds: float = 0.0
    dcn_bytes: int = 0
    ici_bytes: int = 0

    def max_round_host_bytes(self) -> int:
        """Peak per-(host, round) in-flight bytes (sends + recvs)."""
        peak = 0
        for rnd in self.rounds:
            per_host: Dict[str, int] = {}
            for i in rnd:
                e = self.plan.edges[i]
                per_host[e.src_host] = per_host.get(e.src_host, 0) + e.nbytes
                per_host[e.dst_host] = per_host.get(e.dst_host, 0) + e.nbytes
            peak = max(peak, max(per_host.values(), default=0))
        return peak

    def stats(self) -> Dict[str, float]:
        return {
            "num_rounds": len(self.rounds),
            "num_edges": sum(len(r) for r in self.rounds),
            "est_seconds": self.est_seconds,
            "dcn_bytes": self.dcn_bytes,
            "ici_bytes": self.ici_bytes,
            "max_round_host_bytes": self.max_round_host_bytes(),
        }


def lower_collective(plan: TransferPlan,
                     cost_model: Optional[DcnCostModel] = None,
                     inflight_limit_bytes: int = 64 << 20,
                     ) -> RedistributionProgram:
    """Lower ``plan`` into a :class:`RedistributionProgram`.

    Asserts ``plan.no_gather()`` BEFORE lowering — a plan that would
    gather must never reach a transport (raising here is what keeps the
    XLA replicate-then-slice rematerialization fallback dead; see
    :func:`maybe_lower_collective` for the logged fallback).

    Edge order inside the round stream: DCN edges first (they are the
    long poles — issuing them early overlaps them with the intra-node
    traffic), then by descending size. Greedy round packing keeps every
    host's per-round send+recv bytes under ``inflight_limit_bytes``
    (a single edge larger than the limit gets a round of its own rather
    than being rejected — it must move regardless).
    """
    if not plan.no_gather():
        raise ReshardLoweringError(
            "reshard plan is not no-gather: some host would materialize a "
            "full copy of a non-replicated leaf; refusing to lower to "
            "collectives (and never falling back to replicate-then-slice)")
    cm = cost_model or DcnCostModel()
    indexed = [(i, e) for i, e in enumerate(plan.edges) if not e.local]
    indexed.sort(key=lambda ie: (not cm.is_dcn(ie[1]), -ie[1].nbytes,
                                 ie[0]))
    rounds: List[List[int]] = []
    loads: List[Dict[str, int]] = []  # per-round per-host in-flight bytes
    for i, e in indexed:
        placed = False
        for rnd, load in zip(rounds, loads):
            if (load.get(e.src_host, 0) + e.nbytes <= inflight_limit_bytes
                    and load.get(e.dst_host, 0) + e.nbytes
                    <= inflight_limit_bytes):
                rnd.append(i)
                load[e.src_host] = load.get(e.src_host, 0) + e.nbytes
                load[e.dst_host] = load.get(e.dst_host, 0) + e.nbytes
                placed = True
                break
        if not placed:
            rounds.append([i])
            # non-local edges always cross hosts (a same-host intersection
            # is a local edge by construction), so two distinct keys
            loads.append({e.src_host: e.nbytes, e.dst_host: e.nbytes})
    dcn = sum(e.nbytes for _, e in indexed if cm.is_dcn(e))
    ici = sum(e.nbytes for _, e in indexed if not cm.is_dcn(e))
    # est: per round, the slowest host's serialized send time; rounds are
    # sequential by construction
    est = 0.0
    for rnd in rounds:
        per_host: Dict[str, float] = {}
        for i in rnd:
            e = plan.edges[i]
            per_host[e.src_host] = per_host.get(e.src_host, 0.0) \
                + cm.edge_seconds(e)
        est += max(per_host.values(), default=0.0)
    return RedistributionProgram(plan=plan, rounds=rounds, est_seconds=est,
                                 dcn_bytes=dcn, ici_bytes=ici)


# fallback accounting: every place the collective lowering is bypassed is
# counted and logged (rate-limited) — the MULTICHIP_r05 regression was a
# *silent* XLA rematerialization on sharding transitions; silence is the bug
_fallback_lock = threading.Lock()
_fallback_counts: Dict[str, int] = {}
_fallback_last_log: Dict[str, float] = {}
_FALLBACK_LOG_INTERVAL_S = 60.0


def note_lowering_fallback(reason: str, detail: str = "") -> None:
    """Record (and rate-limited-log) one lowering fallback. Never silent:
    the first occurrence of each reason logs immediately, repeats at most
    once per minute per reason."""
    now = time.time()
    with _fallback_lock:
        _fallback_counts[reason] = _fallback_counts.get(reason, 0) + 1
        count = _fallback_counts[reason]
        last = _fallback_last_log.get(reason, 0.0)
        if now - last < _FALLBACK_LOG_INTERVAL_S:
            return
        _fallback_last_log[reason] = now
    logger.warning(
        "weights reshard: collective lowering fell back (%s, %d so far)%s",
        reason, count, f": {detail}" if detail else "")


def lowering_fallback_counts() -> Dict[str, int]:
    with _fallback_lock:
        return dict(_fallback_counts)


def reset_lowering_fallback_counts() -> None:
    with _fallback_lock:
        _fallback_counts.clear()
        _fallback_last_log.clear()


def maybe_lower_collective(plan: TransferPlan,
                           cost_model: Optional[DcnCostModel] = None,
                           inflight_limit_bytes: int = 64 << 20,
                           ) -> Optional[RedistributionProgram]:
    """Best-effort lowering: returns None (after a rate-limited log, never
    silently) when the plan cannot be lowered no-gather. Callers that get
    None fall back to their legacy path knowingly."""
    try:
        return lower_collective(plan, cost_model, inflight_limit_bytes)
    except ReshardLoweringError as e:
        note_lowering_fallback("plan_not_no_gather", str(e))
        return None

"""Model zoo: the flagship decoder LM (dense + MoE) plus ViT and RL nets."""

from ray_tpu.models.transformer import (
    CONFIGS,
    MoEMLP,
    Transformer,
    TransformerConfig,
    lm_loss,
)
from ray_tpu.models.vit import (
    VIT_CONFIGS,
    VisionTransformer,
    ViTConfig,
    accuracy,
    classification_loss,
)

__all__ = [
    "Transformer", "TransformerConfig", "CONFIGS", "MoEMLP", "lm_loss",
    "VisionTransformer", "ViTConfig", "VIT_CONFIGS",
    "classification_loss", "accuracy",
]

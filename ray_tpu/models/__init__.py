"""Model zoo: the flagship decoder LM plus small nets for RL/vision tests."""

from ray_tpu.models.transformer import (
    CONFIGS,
    Transformer,
    TransformerConfig,
    lm_loss,
)

__all__ = ["Transformer", "TransformerConfig", "CONFIGS", "lm_loss"]

"""MLP actor-critic for RL (reference capability: rllib RLModule default
MLP nets, core/rl_module/). Discrete-action policy + value head in flax."""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class QNetwork(nn.Module):
    """State-action value MLP for DQN-family algorithms."""

    action_dim: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs: jax.Array) -> jax.Array:
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"torso_{i}")(x))
        return nn.Dense(self.action_dim, name="q",
                        kernel_init=nn.initializers.orthogonal(0.01))(x)


class ActorCritic(nn.Module):
    action_dim: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.tanh(nn.Dense(h, name=f"torso_{i}")(x))
        logits = nn.Dense(self.action_dim, name="pi",
                          kernel_init=nn.initializers.orthogonal(0.01))(x)
        v = nn.Dense(1, name="vf", kernel_init=nn.initializers.orthogonal(1.0))(x)
        return logits, v[..., 0]

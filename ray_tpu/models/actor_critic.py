"""MLP actor-critic for RL (reference capability: rllib RLModule default
MLP nets, core/rl_module/). Discrete-action policy + value head in flax."""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class QNetwork(nn.Module):
    """State-action value MLP for DQN-family algorithms."""

    action_dim: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs: jax.Array) -> jax.Array:
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, name=f"torso_{i}")(x))
        return nn.Dense(self.action_dim, name="q",
                        kernel_init=nn.initializers.orthogonal(0.01))(x)


class ActorCritic(nn.Module):
    action_dim: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.tanh(nn.Dense(h, name=f"torso_{i}")(x))
        logits = nn.Dense(self.action_dim, name="pi",
                          kernel_init=nn.initializers.orthogonal(0.01))(x)
        v = nn.Dense(1, name="vf", kernel_init=nn.initializers.orthogonal(1.0))(x)
        return logits, v[..., 0]


class SquashedGaussianActor(nn.Module):
    """Tanh-squashed diagonal Gaussian policy for continuous control
    (reference: rllib SAC's action distribution). ``sample`` returns
    (action in [-1,1]^d, log_prob with the tanh change-of-variables
    correction)."""

    act_dim: int
    hidden: Tuple[int, ...] = (128, 128)
    log_std_min: float = -10.0
    log_std_max: float = 2.0

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        mu = nn.Dense(self.act_dim)(x)
        log_std = nn.Dense(self.act_dim)(x)
        log_std = jnp.clip(log_std, self.log_std_min, self.log_std_max)
        return mu, log_std

    def sample(self, obs: jax.Array, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        mu, log_std = self(obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mu.shape)
        pre_tanh = mu + std * eps
        action = jnp.tanh(pre_tanh)
        # log N(pre_tanh; mu, std) - sum log(1 - tanh^2)
        logp = (-0.5 * (((pre_tanh - mu) / std) ** 2
                        + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)
        logp -= (2 * (jnp.log(2.0) - pre_tanh
                      - jax.nn.softplus(-2 * pre_tanh))).sum(-1)
        return action, logp


class ContinuousQ(nn.Module):
    """Q(s, a) head for continuous actions (reference: SAC twin critics)."""

    hidden: Tuple[int, ...] = (128, 128)

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(1)(x)[..., 0]

"""Vision Transformer classifier (second model family).

Reference capability: the reference trains vision models through torch in
user code (rllib CNNs, train examples); here the ViT is framework-native
flax with the same logical sharding vocabulary as the LM — patch/TP
shardings resolve against any mesh, so DP/FSDP/TP apply unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    num_classes: int = 10
    d_model: int = 192
    n_layers: int = 6
    n_heads: int = 6
    d_ff: int = 768
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


VIT_CONFIGS = {
    "vit-tiny": ViTConfig(),
    "vit-s16-224": ViTConfig(image_size=224, patch_size=16, num_classes=1000,
                             d_model=384, n_layers=12, n_heads=6, d_ff=1536),
    "vit-b16-224": ViTConfig(image_size=224, patch_size=16, num_classes=1000,
                             d_model=768, n_layers=12, n_heads=12, d_ff=3072),
}


class EncoderBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=cfg.n_heads, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            deterministic=deterministic, name="attn")(h, h)
        x = x + h
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x)
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="fc1",
                     kernel_init=nn.with_logical_partitioning(
                         nn.initializers.xavier_uniform(), ("embed", "mlp")))(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.d_model, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="fc2",
                     kernel_init=nn.with_logical_partitioning(
                         nn.initializers.xavier_uniform(), ("mlp", "embed")))(h)
        return x + h


class VisionTransformer(nn.Module):
    """(B, H, W, C) images -> (B, num_classes) logits."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, deterministic: bool = True):
        cfg = self.cfg
        B = images.shape[0]
        x = nn.Conv(cfg.d_model, kernel_size=(cfg.patch_size, cfg.patch_size),
                    strides=(cfg.patch_size, cfg.patch_size),
                    dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                    name="patch_embed")(images.astype(cfg.dtype))
        x = x.reshape(B, -1, cfg.d_model)  # (B, P, D)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.d_model), cfg.param_dtype)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(cfg.dtype), (B, 1, cfg.d_model)), x],
            axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (1, cfg.num_patches + 1, cfg.d_model), cfg.param_dtype)
        x = x + pos.astype(cfg.dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"block_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_final")(x)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, name="head")(x[:, 0])


def classification_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return (jnp.argmax(logits, -1) == labels).mean()

"""Flagship decoder-only transformer LM (llama-family), TPU-first.

This is the model the framework's north-star path trains (SURVEY.md §3.4):
GSPMD-sharded via logical axis annotations so one definition serves DP, FSDP,
TP, and SP meshes (reference capability: Ray delegates model parallelism to
torch; here it is native — flax linen + ``nn.with_logical_partitioning``).

Design notes for the MXU:
- all matmuls are bf16 with fp32 accumulation (``preferred_element_type``);
- weights are stored fp32 (master) and cast to the compute dtype per step;
- attention goes through ``ray_tpu.ops.attention`` (pallas flash kernel on
  TPU, pure-jax fallback elsewhere);
- remat policy checkpoints per block to trade FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.attention import attention as attention_op

# Logical axis names used across the parallel layer (see
# ray_tpu/parallel/mesh.py for the logical->mesh rules).
BATCH = "batch"
SEQ = "seq"
EMBED = "embed"
HEADS = "heads"
KV_HEADS = "kv_heads"
HEAD_DIM = "head_dim"
MLP = "mlp"
VOCAB = "vocab"


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 1408
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    attention_impl: str = "auto"  # auto | flash | xla
    # mixture-of-experts (0 experts = dense MLP); experts shard over the
    # mesh "expert" axis (EP) and tokens reach them via the one-hot
    # dispatch einsums XLA lowers to all-to-alls (GShard style)
    n_experts: int = 0
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    moe_every: int = 1  # every Nth block uses MoE (others stay dense)
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        d, f, v = self.d_model, self.d_ff, self.vocab_size
        attn = (
            d * d  # q
            + 2 * d * (self.n_kv_heads * self.head_dim)  # k, v
            + d * d  # o
            + 2 * d  # norms
        )
        dense_mlp = 3 * d * f
        total = 0
        for i in range(self.n_layers):
            moe = self.n_experts > 0 and i % max(self.moe_every, 1) == 0
            total += attn + (self.n_experts * 3 * d * f + d * self.n_experts
                             if moe else dense_mlp)
        return v * d + total + d + (0 if self.tie_embeddings else d * v)

    def active_params(self) -> int:
        """Params touched per token: MoE layers count only the
        experts_per_token experts a token is routed to (MFU accounting)."""
        if self.n_experts == 0:
            return self.num_params()
        d, f = self.d_model, self.d_ff
        total = self.num_params()
        for i in range(self.n_layers):
            if i % max(self.moe_every, 1) == 0:
                inactive = self.n_experts - self.experts_per_token
                total -= inactive * 3 * d * f
        return total

    def flops_per_token(self) -> float:
        """Approximate training FLOPs/token (fwd+bwd ~ 6*N_active +
        attention)."""
        return (6.0 * self.active_params()
                + 12.0 * self.n_layers * self.d_model * self.max_seq_len)


# preset configs (name -> config); "tiny" is the CI/test config
CONFIGS = {
    "tiny": TransformerConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                              n_kv_heads=2, d_ff=128, max_seq_len=128, remat=False),
    "125m": TransformerConfig(vocab_size=32000, d_model=768, n_layers=12, n_heads=12,
                              n_kv_heads=12, d_ff=2048, max_seq_len=2048),
    "350m": TransformerConfig(vocab_size=32000, d_model=1024, n_layers=24, n_heads=16,
                              n_kv_heads=16, d_ff=2816, max_seq_len=2048),
    "1b": TransformerConfig(vocab_size=32000, d_model=2048, n_layers=16, n_heads=16,
                            n_kv_heads=8, d_ff=5632, max_seq_len=2048),
    "7b": TransformerConfig(vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
                            n_kv_heads=32, d_ff=11008, max_seq_len=4096),
    "moe-tiny": TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, max_seq_len=128, remat=False, n_experts=4,
        experts_per_token=2),
    "moe-1b": TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=16, n_heads=16, n_kv_heads=16,
        d_ff=2816, max_seq_len=2048, n_experts=8, experts_per_token=2,
        moe_every=2),
}


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding over the last dim (pairs)."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (..., S, half)
    cos = jnp.cos(angles)[:, :, None, :]  # (B, S, 1, half)
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale", nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
            (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(self.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        B, S, _ = x.shape
        hd = cfg.head_dim
        dense = lambda feats, axes, name: nn.DenseGeneral(  # noqa: E731
            features=feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02 / np.sqrt(2 * cfg.n_layers)), axes),
        )
        q = dense((cfg.n_heads, hd), ("embed", "heads", "head_dim"), "q_proj")(x)
        k = dense((cfg.n_kv_heads, hd), ("embed", "kv_heads", "head_dim"), "k_proj")(x)
        v = dense((cfg.n_kv_heads, hd), ("embed", "kv_heads", "head_dim"), "v_proj")(x)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        if cfg.n_kv_heads != cfg.n_heads:
            rep = cfg.n_heads // cfg.n_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        out = attention_op(q, k, v, causal=True, impl=cfg.attention_impl,
                           segment_ids=segment_ids)
        out = nn.DenseGeneral(
            features=cfg.d_model, axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="o_proj",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02 / np.sqrt(2 * cfg.n_layers)),
                ("heads", "head_dim", "embed")),
        )(out)
        return out


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, axes, name: nn.DenseGeneral(  # noqa: E731
            features=feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02 / np.sqrt(2 * cfg.n_layers)), axes),
        )
        gate = dense(cfg.d_ff, ("embed", "mlp"), "gate_proj")(x)
        up = dense(cfg.d_ff, ("embed", "mlp"), "up_proj")(x)
        hidden = nn.silu(gate) * up
        return nn.DenseGeneral(
            features=cfg.d_model, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="down_proj",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02 / np.sqrt(2 * cfg.n_layers)), ("mlp", "embed")),
        )(hidden)


class MoEMLP(nn.Module):
    """Top-k routed mixture-of-experts MLP (GShard-style dense dispatch).

    Reference capability: the reference delegates MoE to vLLM/torch user
    code; here EP is native — expert-stacked weights carry the "expert"
    logical axis, the one-hot dispatch/combine einsums keep everything on
    the MXU, and XLA inserts the expert all-to-alls implied by the
    shardings. Token capacity is bounded (capacity_factor); overflow
    tokens fall through the residual (standard token dropping). The
    load-balancing aux loss is sown under the "losses" collection."""

    cfg: TransformerConfig

    GROUP_SIZE = 4096  # tokens per dispatch group (bounds one-hot memory)

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, S, D = x.shape
        E, K = cfg.n_experts, cfg.experts_per_token
        N = B * S
        # GShard-style grouping: dispatch/combine one-hots are O(g*E*C) per
        # group with C ~ g*K/E, so memory/FLOPs stay linear in N instead of
        # quadratic (tokens only compete for capacity within their group)
        g = N
        for cand in range(min(self.GROUP_SIZE, N), 0, -1):
            if N % cand == 0:
                g = cand
                break
        G = N // g
        C = max(1, int(cfg.capacity_factor * g * K / E))
        xf = x.reshape(G, g, D)

        router = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="router",
                          kernel_init=nn.with_logical_partitioning(
                              nn.initializers.normal(0.02), ("embed", "expert")))
        logits = router(xf.astype(jnp.float32))  # (G, g, E)
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k expert choice per token
        gate_vals, expert_idx = jax.lax.top_k(probs, K)  # (G, g, K)
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)

        # position of each (token, k) within its expert's capacity buffer,
        # per group; k-slots of a token are ordered before later tokens
        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # (G, g, K, E)
        flat = onehot.reshape(G, g * K, E)
        pos_in_expert = (jnp.cumsum(flat, axis=1) - flat).reshape(G, g, K, E)
        pos = (pos_in_expert * onehot).sum(-1)  # (G, g, K)
        keep = pos < C

        # dispatch/combine (G, g, E, C)
        eh = jax.nn.one_hot(expert_idx, E, dtype=cfg.dtype)[..., None]
        ph = jax.nn.one_hot(pos, C, dtype=cfg.dtype)[..., None, :]
        dispatch = (eh * ph * keep[..., None, None].astype(cfg.dtype)).sum(2)
        combine = (eh * ph
                   * (gate_vals * keep)[..., None, None].astype(cfg.dtype)).sum(2)

        expert_in = jnp.einsum("gnec,gnd->gecd", dispatch, xf)
        expert_in = nn.with_logical_constraint(
            expert_in, (None, "expert", None, "embed"))

        def stack_param(name, shape, axes):
            return self.param(
                name, nn.with_logical_partitioning(
                    nn.initializers.normal(0.02 / np.sqrt(2 * cfg.n_layers)),
                    axes),
                shape, cfg.param_dtype)

        w_gate = stack_param("gate_proj", (E, D, cfg.d_ff),
                             ("expert", "embed", "mlp"))
        w_up = stack_param("up_proj", (E, D, cfg.d_ff),
                           ("expert", "embed", "mlp"))
        w_down = stack_param("down_proj", (E, cfg.d_ff, D),
                             ("expert", "mlp", "embed"))
        h = (nn.silu(jnp.einsum("gecd,edf->gecf", expert_in,
                                w_gate.astype(cfg.dtype)))
             * jnp.einsum("gecd,edf->gecf", expert_in, w_up.astype(cfg.dtype)))
        expert_out = jnp.einsum("gecf,efd->gecd", h, w_down.astype(cfg.dtype))
        expert_out = nn.with_logical_constraint(
            expert_out, (None, "expert", None, "embed"))
        out = jnp.einsum("gnec,gecd->gnd", combine, expert_out)

        # load-balancing loss (Switch/GShard): E * sum_e f_e * p_e
        token_frac = jnp.mean(
            jax.nn.one_hot(expert_idx[..., 0], E, dtype=jnp.float32),
            axis=(0, 1))
        prob_frac = jnp.mean(probs, axis=(0, 1))
        aux = E * jnp.sum(token_frac * prob_frac)
        self.sow("losses", "moe_aux", aux)
        return out.reshape(B, S, D)


class Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        h = x + Attention(cfg, name="attn")(
            RMSNorm(dtype=cfg.dtype, name="attn_norm")(x), positions, segment_ids)
        h = nn.with_logical_constraint(h, ("batch", "seq", "embed"))
        mlp = MoEMLP(cfg, name="moe") if self.use_moe else MLP(cfg, name="mlp")
        out = h + mlp(RMSNorm(dtype=cfg.dtype, name="mlp_norm")(h))
        return nn.with_logical_constraint(out, ("batch", "seq", "embed"))


class Transformer(nn.Module):
    """Decoder-only LM. __call__ returns logits (B, S, V)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions=None, segment_ids=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.arange(tokens.shape[1])[None, :].astype(jnp.int32)
            positions = jnp.broadcast_to(positions, tokens.shape)
        embed = self.param(
            "embed", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        x = embed.astype(cfg.dtype)[tokens]
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        block = Block
        if cfg.remat:
            block = nn.remat(Block, prevent_cse=False,
                             policy=jax.checkpoint_policies.nothing_saveable)
        for i in range(cfg.n_layers):
            use_moe = cfg.n_experts > 0 and i % max(cfg.moe_every, 1) == 0
            x = block(cfg, use_moe, name=f"layer_{i}")(
                x, positions, segment_ids)
        x = RMSNorm(dtype=cfg.dtype, name="final_norm")(x)
        if cfg.tie_embeddings:
            logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(cfg.dtype))
        else:
            head = self.param(
                "lm_head", nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), ("embed", "vocab")),
                (cfg.d_model, cfg.vocab_size), cfg.param_dtype)
            logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype),
                                preferred_element_type=jnp.float32)
        return nn.with_logical_constraint(logits, ("batch", "seq", "vocab"))


def lm_loss(logits: jax.Array, targets: jax.Array,
            mask: Optional[jax.Array] = None) -> jax.Array:
    """Next-token cross entropy; `targets` are the inputs shifted by one."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return nll.mean()

"""Device object transport: tensors stay where they were produced.

Reference: python/ray/experimental/gpu_object_manager (``@ray.method(
tensor_transport=...)``, per-actor GPUObjectStore, driver-side orchestration
of p2p pulls) — re-architected for TPU: the value returned by a marked actor
method stays in the producing worker (device memory for jax arrays), and a
small ``DeviceObjectMarker`` travels through the object plane instead.
Consumers (other actors, or the driver) pull the value directly from the
holder worker — the driver never relays tensor bytes between actors. On a
real slice the pull lowers to host-mediated transfer today; the marker
carries the transport tag so an ICI path can slot in without API change.

Usage::

    class Producer:
        @ray_tpu.method(tensor_transport="device")
        def weights(self):
            return jnp.ones((4096, 4096))

    ref = producer.weights.remote()     # returns instantly; value stays put
    consumer.consume.remote(ref)        # consumer pulls p2p from producer
    ray_tpu.get(ref)                    # driver pulls from producer
"""

from __future__ import annotations

from ray_tpu._private import wire
from typing import Any, Optional


class DeviceObjectMarker:
    """Placeholder for a value held in a producer worker's device store."""

    __slots__ = ("oid", "address", "transport")

    def __init__(self, oid: bytes, address: str, transport: str = "device"):
        self.oid = oid
        self.address = address
        self.transport = transport

    def __reduce__(self):
        return (DeviceObjectMarker, (self.oid, self.address, self.transport))

    def __repr__(self):
        return (f"DeviceObjectMarker({self.oid.hex()[:12]} @ {self.address}, "
                f"{self.transport})")


def free(ref) -> bool:
    """Release the device-held value behind ``ref`` on its holder worker.
    Returns False if the value was already gone."""
    import pickle
    import time

    from ray_tpu._private.worker import global_worker

    core = global_worker()
    # resolve the MARKER itself (core.get would pull the tensor)
    marker = core._run(core._get_one(ref, time.monotonic() + 60.0))
    if not isinstance(marker, DeviceObjectMarker):
        raise TypeError("free() expects a ref produced by a "
                        "tensor_transport-marked method")

    async def _free():
        reply = await core._worker_client(marker.address).call(
            "FreeDeviceObject", wire.dumps({"oid": marker.oid}), timeout=30.0)
        return wire.loads(reply)["freed"]

    return core._run(_free())

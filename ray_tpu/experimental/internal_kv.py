"""Internal KV API over the GCS KV table (reference:
python/ray/experimental/internal_kv.py; server side gcs_kv_manager.cc).
Durable across GCS restarts when the cluster runs with GCS fault
tolerance (see store_client.py)."""

from __future__ import annotations

from typing import List, Optional


def _core():
    from ray_tpu._private.worker import global_worker

    return global_worker()


def _call(method: str, req: dict) -> dict:
    core = _core()
    return core._run(core._gcs_call(method, req))


def _internal_kv_initialized() -> bool:
    from ray_tpu._private.worker import is_initialized

    return is_initialized()


def _internal_kv_put(key: bytes, value: bytes, overwrite: bool = True,
                     namespace: str = "") -> bool:
    """Returns True if the key was already present and NOT overwritten."""
    reply = _call("KVPut", {"ns": namespace, "key": _s(key), "value": value,
                            "overwrite": overwrite})
    return not reply["added"]


def _internal_kv_get(key: bytes, namespace: str = "") -> Optional[bytes]:
    return _call("KVGet", {"ns": namespace, "key": _s(key)})["value"]


def _internal_kv_exists(key: bytes, namespace: str = "") -> bool:
    return _internal_kv_get(key, namespace) is not None


def _internal_kv_del(key: bytes, del_by_prefix: bool = False,
                     namespace: str = "") -> int:
    return _call("KVDel", {"ns": namespace, "key": _s(key),
                           "prefix": del_by_prefix})["deleted"]


def _internal_kv_list(prefix: bytes, namespace: str = "") -> List[bytes]:
    keys = _call("KVKeys", {"ns": namespace, "prefix": _s(prefix)})["keys"]
    return [k.encode() if isinstance(k, str) else k for k in keys]


def _s(key) -> str:
    return key.decode() if isinstance(key, (bytes, bytearray)) else key

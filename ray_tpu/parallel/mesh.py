"""Device meshes and logical sharding rules.

The framework's parallelism vocabulary (the idiomatic superset of what the
reference delegates to torch — SURVEY.md §7 step 7):

- ``data``: pure data parallel (batch)
- ``fsdp``: data parallel with parameter sharding (ZeRO-3/GSPMD style)
- ``seq``: sequence/context parallelism (ring attention / Ulysses)
- ``tensor``: megatron-style tensor parallelism (heads / mlp / vocab)
- ``expert``: MoE expert parallelism

A mesh is just ``jax.sharding.Mesh`` over these named axes; logical axis
names used by the models map onto mesh axes via LOGICAL_RULES, and XLA
inserts the collectives (psum/all-gather/reduce-scatter over ICI) implied by
the shardings.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.utils import import_jax

AXES = ("data", "fsdp", "seq", "tensor", "expert")

# logical axis -> mesh axis (or tuple) mapping; None = replicated
LOGICAL_RULES = (
    ("batch", ("data", "fsdp")),
    ("seq", "seq"),
    ("embed", "fsdp"),
    ("heads", "tensor"),
    ("kv_heads", "tensor"),
    ("head_dim", None),
    ("mlp", "tensor"),
    ("vocab", "tensor"),
    ("expert", "expert"),
)


def create_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None):
    """Build a Mesh with named axes; sizes must multiply to #devices."""
    jax = import_jax()
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else list(jax.devices())
    total = int(np.prod(list(axes.values()))) if axes else 1
    if total != len(devs):
        raise ValueError(f"mesh axes {axes} need {total} devices, have {len(devs)}")
    names = tuple(axes.keys())
    shape = tuple(axes.values())
    return Mesh(np.array(devs).reshape(shape), names)


def default_mesh_axes(n_devices: int) -> Dict[str, int]:
    """A sensible decomposition for n devices: tensor within host-ICI reach,
    fsdp for the rest (pure-dp kept 1; scale dp across slices via DCN)."""
    tensor = 1
    for cand in (8, 4, 2):
        if n_devices % cand == 0 and n_devices >= cand * 2:
            tensor = cand
            break
    if n_devices <= 4:
        tensor = 1
    return {"data": 1, "fsdp": n_devices // tensor, "seq": 1, "tensor": tensor,
            "expert": 1}


def logical_to_mesh_sharding(logical_spec_tree, mesh, rules=LOGICAL_RULES):
    import flax.linen as nn

    return nn.logical_to_mesh_sharding(logical_spec_tree, mesh, list(rules))


def named_sharding(mesh, *spec):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))

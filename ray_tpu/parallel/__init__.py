"""Parallelism library: meshes, logical rules, GSPMD train step, SP/CP."""

from ray_tpu.parallel.mesh import (
    AXES,
    LOGICAL_RULES,
    create_mesh,
    default_mesh_axes,
    named_sharding,
)
from ray_tpu.parallel.train import (
    TrainStepBundle,
    make_optimizer,
    sharded_clip_by_global_norm,
)

__all__ = [
    "AXES",
    "LOGICAL_RULES",
    "create_mesh",
    "default_mesh_axes",
    "named_sharding",
    "TrainStepBundle",
    "make_optimizer",
    "sharded_clip_by_global_norm",
]

"""GSPMD training step for the flagship model.

Builds the jitted train step the Train layer runs on every host (SURVEY.md
§3.4 — the reference only launches processes; here the compute path is part
of the framework): optax optimizer, bf16 compute / fp32 params, logical
shardings resolved against the mesh so DP/FSDP/TP/SP all come from the same
definition.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ray_tpu.models.transformer import Transformer, TransformerConfig, lm_loss
from ray_tpu.parallel.mesh import LOGICAL_RULES, logical_to_mesh_sharding
from ray_tpu.utils import import_jax

_metrics_lock = threading.Lock()
_metrics: Optional[dict] = None


def _obs() -> dict:
    """Lazily-created train-step metrics on the shared registry (always
    on: every step through TrainStepBundle lands in ``/metrics``)."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Histogram

            bounds = [0.001, 0.01, 0.1, 1, 10]
            _metrics = {
                "step": Histogram(
                    "ray_tpu.train.step_seconds",
                    "full train step wall time (fwd+bwd+optimizer; "
                    "device-synchronized when tracing is enabled)",
                    boundaries=bounds),
                "fwd_bwd": Histogram(
                    "ray_tpu.train.fwd_bwd_seconds",
                    "forward+backward (value_and_grad) phase of the "
                    "traced train step", boundaries=bounds),
                "optimizer": Histogram(
                    "ray_tpu.train.optimizer_seconds",
                    "optimizer update+apply phase of the traced train "
                    "step", boundaries=bounds),
            }
        return _metrics


def make_optimizer(learning_rate: float = 3e-4, weight_decay: float = 0.1,
                   warmup_steps: int = 100, total_steps: int = 10000,
                   b1: float = 0.9, b2: float = 0.95, clip: float = 1.0):
    import optax

    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


class TrainStepBundle:
    """Everything a training worker needs: init fn, step fn, shardings."""

    def __init__(self, cfg: TransformerConfig, mesh, optimizer=None,
                 rules=LOGICAL_RULES, donate: bool = True):
        jax = import_jax()
        import flax.linen as nn
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.cfg = cfg
        self.mesh = mesh
        self.model = Transformer(cfg)
        self.optimizer = optimizer or make_optimizer()
        self.rules = rules

        def init_fn(rng):
            B, S = 1, min(cfg.max_seq_len, 128)
            tokens = jax.numpy.zeros((B, S), dtype=jax.numpy.int32)
            params = self.model.init(rng, tokens)["params"]
            opt_state = self.optimizer.init(params)
            return params, opt_state

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        logical = nn.get_partition_spec(abstract)
        shardings = logical_to_mesh_sharding(logical, mesh, rules)
        self.param_shardings, self.opt_shardings = shardings
        self.batch_sharding = NamedSharding(mesh, P(("data", "fsdp"), "seq"))
        self.repl = NamedSharding(mesh, P())

        self.init = jax.jit(init_fn, out_shardings=shardings)

        def loss_fn(params, tokens, targets, mask):
            # "losses" is valid for dense models too (empty -> aux sums to 0)
            logits, cols = self.model.apply(
                {"params": params}, tokens, mutable=["losses"])
            aux = sum(jax.tree.leaves(cols.get("losses", {})))
            return lm_loss(logits, targets, mask) + cfg.moe_aux_coef * aux

        def train_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch["tokens"], batch["targets"], batch.get("mask"))
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        batch_shardings = {"tokens": self.batch_sharding,
                           "targets": self.batch_sharding,
                           "mask": self.batch_sharding}
        donate_args = (0, 1) if donate else ()
        self._fused_step = jax.jit(
            train_step,
            in_shardings=(self.param_shardings, self.opt_shardings,
                          batch_shardings),
            out_shardings=(self.param_shardings, self.opt_shardings, self.repl),
            donate_argnums=donate_args,
        )

        # phase-split programs for the TRACED step (fwd+bwd and optimizer
        # as separate XLA programs, so tracing.profile() spans can bound
        # each phase); the untraced path keeps the fused program — and its
        # fusion/donation — untouched
        def fwd_bwd(params, batch):
            return jax.value_and_grad(loss_fn)(
                params, batch["tokens"], batch["targets"], batch.get("mask"))

        self._fwd_bwd = jax.jit(
            fwd_bwd,
            in_shardings=(self.param_shardings, batch_shardings),
            out_shardings=(self.repl, self.param_shardings),
        )

        def opt_apply(grads, opt_state, params):
            import optax

            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._opt_apply = jax.jit(
            opt_apply,
            in_shardings=(self.param_shardings, self.opt_shardings,
                          self.param_shardings),
            out_shardings=(self.param_shardings, self.opt_shardings),
            # donate opt_state + params (consumed, re-emitted); grads stay
            # undonated — XLA can't alias them onto the outputs here and
            # would warn on every traced step
            donate_argnums=(1, 2) if donate else (),
        )

        def eval_step(params, batch):
            logits, _ = self.model.apply(
                {"params": params}, batch["tokens"], mutable=["losses"])
            return lm_loss(logits, batch["targets"], batch.get("mask"))

        self.eval_step = jax.jit(eval_step)

    def step(self, params, opt_state, batch):
        """One optimization step, instrumented (built-in spans + the
        ``ray_tpu.train.*`` histograms — no manual instrumentation in the
        train loop). With tracing OFF this dispatches the single fused XLA
        program, identical to the uninstrumented path; with tracing ON the
        step runs as separately-jitted fwd/bwd and optimizer programs with
        a ``train.step`` span tree bounding each phase, so Perfetto shows
        where the step time goes."""
        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        if not tracing.enabled():
            out = self._fused_step(params, opt_state, batch)
            _obs()["step"].observe(time.perf_counter() - t0)
            return out
        jax = import_jax()
        obs = _obs()
        with tracing.profile("train.step", category="train"):
            with tracing.profile("train.fwd_bwd", category="train"):
                t1 = time.perf_counter()
                loss, grads = self._fwd_bwd(params, batch)
                jax.block_until_ready(grads)
                obs["fwd_bwd"].observe(time.perf_counter() - t1)
            with tracing.profile("train.optimizer", category="train"):
                t2 = time.perf_counter()
                params, opt_state = self._opt_apply(grads, opt_state, params)
                jax.block_until_ready(params)
                obs["optimizer"].observe(time.perf_counter() - t2)
        obs["step"].observe(time.perf_counter() - t0)
        return params, opt_state, loss

    def make_batch(self, rng: np.random.Generator, batch_size: int, seq_len: int):
        """Synthetic LM batch (tokens/targets/mask) laid out for the mesh."""
        jax = import_jax()

        tokens = rng.integers(0, self.cfg.vocab_size, (batch_size, seq_len + 1),
                              dtype=np.int32)
        batch = {
            "tokens": tokens[:, :-1],
            "targets": tokens[:, 1:],
            "mask": np.ones((batch_size, seq_len), np.float32),
        }
        return {k: jax.device_put(v, self.batch_sharding) for k, v in batch.items()}

"""GSPMD training step for the flagship model.

Builds the jitted train step the Train layer runs on every host (SURVEY.md
§3.4 — the reference only launches processes; here the compute path is part
of the framework): optax optimizer, bf16 compute / fp32 params, logical
shardings resolved against the mesh so DP/FSDP/TP/SP all come from the same
definition.

Overlapped + cross-replica-sharded update (see OVERLAP.md next to this
file; T3 arxiv 2401.16677 + weight-update sharding arxiv 2004.13336):
with ``shard_update=True`` (opt-in; needs a mesh ``data`` axis > 1),
optimizer state and the update computation are sharded across the data axis — grads
leave the backward as a reduce-scatter instead of an all-reduce, each
replica updates its 1/N slice, and the refreshed params all-gather back.
Expressed three ways:

- **untraced sharded step** (the perf path): ONE jitted program with
  shard-annotated opt state + donated buffers; XLA's async collectives
  overlap the grad reduce-scatter with the tail of the backward and the
  param all-gather with the update — and it is **bit-exact in fp32**
  against the fused unsharded step (same-program codegen, pinned-
  association global-norm clip; asserted in tests/test_train.py).
- **traced sharded step** (observability): phase-split programs — a
  shard_map backward emitting per-replica local grads, then one jitted
  reduce-scatter program PER BUCKET (size-bounded layer-order buckets,
  ``bucket_bytes``) dispatched asynchronously, then the sharded optimizer
  program. Each bucket lands as a ``train.bucket_allreduce`` span nested
  under ``train.fwd_bwd`` in ``/api/timeline``.
- the **fused single-program step** stays the untraced / 1-replica
  fallback, byte-identical behavior to previous releases when
  ``shard_update`` is off.
"""

from __future__ import annotations

import threading
import time
from contextlib import nullcontext as _nullcontext
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.models.transformer import Transformer, TransformerConfig, lm_loss
from ray_tpu.parallel.mesh import AXES, LOGICAL_RULES, logical_to_mesh_sharding
from ray_tpu.utils import import_jax

_metrics_lock = threading.Lock()
_metrics: Optional[dict] = None


def _obs() -> dict:
    """Lazily-created train-step metrics on the shared registry (always
    on: every step through TrainStepBundle lands in ``/metrics``)."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Histogram

            bounds = [0.001, 0.01, 0.1, 1, 10]
            _metrics = {
                "step": Histogram(
                    "ray_tpu.train.step_seconds",
                    "full train step wall time (fwd+bwd+optimizer; "
                    "device-synchronized when tracing is enabled)",
                    boundaries=bounds),
                "fwd_bwd": Histogram(
                    "ray_tpu.train.fwd_bwd_seconds",
                    "forward+backward (value_and_grad) phase of the "
                    "traced train step", boundaries=bounds),
                "optimizer": Histogram(
                    "ray_tpu.train.optimizer_seconds",
                    "optimizer update+apply phase of the traced train "
                    "step", boundaries=bounds),
                "bucket_rs": Histogram(
                    "ray_tpu.train.bucket_reduce_seconds",
                    "per-bucket grad reduce-scatter program wall time on "
                    "the traced sharded step", boundaries=bounds),
            }
        return _metrics


def sharded_clip_by_global_norm(max_norm: float,
                                spec_fn: Optional[Callable] = None):
    """``optax.clip_by_global_norm`` with the global norm computed from
    shard-local sqnorms under a PINNED association.

    ``spec_fn(shape) -> Optional[NamedSharding]`` fixes each leaf's
    reduction layout with ``with_sharding_constraint`` before the sqnorm,
    so the partitioner computes per-shard partial sums + a rank-ordered
    cross-replica sum IDENTICALLY in every program that embeds this clip
    (the fused step, the sharded single-program step, and the split
    optimizer program) — which is what makes the sharded update bit-exact
    against the fused step. With ``spec_fn=None`` the association is the
    leaf-local one (single-replica case)."""
    import optax

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        jax = import_jax()
        import jax.numpy as jnp

        del params

        def sq(x):
            xs = x.astype(jnp.float32)
            spec = spec_fn(tuple(x.shape)) if spec_fn is not None else None
            if spec is not None:
                xs = jax.lax.with_sharding_constraint(xs, spec)
            return jnp.sum(jnp.square(xs))

        leaves = [sq(x) for x in jax.tree_util.tree_leaves(updates)]
        acc = leaves[0]
        for leaf in leaves[1:]:  # explicit fold: the tree order IS the
            acc = acc + leaf     # cross-program contract
        g_norm = jnp.sqrt(acc)
        factor = max_norm / jnp.maximum(g_norm, max_norm)
        updates = jax.tree_util.tree_map(
            lambda u: u * factor.astype(u.dtype), updates)
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(learning_rate: float = 3e-4, weight_decay: float = 0.1,
                   warmup_steps: int = 100, total_steps: int = 10000,
                   b1: float = 0.9, b2: float = 0.95, clip: float = 1.0,
                   clip_spec_fn: Optional[Callable] = None):
    """AdamW + global-norm clip. ``clip_spec_fn`` switches the clip to the
    sharded (pinned-association) form — TrainStepBundle passes its update
    shardings here when ``shard_update`` is on; the default stays plain
    ``optax.clip_by_global_norm`` (bit-identical to previous releases)."""
    import optax

    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    clip_t = (sharded_clip_by_global_norm(clip, clip_spec_fn)
              if clip_spec_fn is not None else optax.clip_by_global_norm(clip))
    return optax.chain(
        clip_t,
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


class TrainStepBundle:
    """Everything a training worker needs: init fn, step fn, shardings.

    ``shard_update=True`` (opt-in; requires a mesh ``data`` axis > 1)
    turns on the cross-replica sharded optimizer update — the caller
    must then hold opt state on the sharded layout (``init_sharded`` /
    ``shard_opt_state``); ``bucket_bytes`` bounds the grad buckets the
    traced path reduces individually. ``optimizer_factory(clip_spec_fn)`` lets the
    caller parameterize the optimizer while still receiving the bundle's
    update shardings for the pinned-association clip (pass ``optimizer=``
    for a fixed transform — bit-parity of the sharded step then depends
    on that transform using ``sharded_clip_by_global_norm``)."""

    def __init__(self, cfg: TransformerConfig, mesh, optimizer=None,
                 rules=LOGICAL_RULES, donate: bool = True,
                 shard_update: bool = False,
                 bucket_bytes: int = 32 << 20,
                 optimizer_factory: Optional[Callable] = None,
                 grad_dtype: str = "fp32",
                 compression: Optional[str] = None):
        jax = import_jax()
        import flax.linen as nn
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.collective.quant import resolve_codec

        self.cfg = cfg
        self.mesh = mesh
        self.model = Transformer(cfg)
        self.rules = rules
        self.bucket_bytes = bucket_bytes
        if grad_dtype not in ("fp32", "bf16"):
            raise ValueError(f"grad_dtype must be fp32 or bf16, got "
                             f"{grad_dtype!r}")
        # "bf16": grads are narrowed to bf16 for the cross-replica
        # reduce-scatter (half the collective bytes; explicit on the
        # traced bucket programs, a value-narrowing cast pair on the
        # one-program path) while optimizer state and params stay fp32
        # master copies. Default "fp32" keeps every program bit-identical
        # to previous releases.
        self.grad_dtype = grad_dtype
        # block-quantized wire for the traced bucket programs (the
        # EQuARX-style XLA tier): each data-sharded leaf's reduce-scatter
        # becomes quantize -> all_to_all (uint8 codes + fp32 block scales
        # on the wire) -> fp32 dequant-accumulate. Strictly opt-in; the
        # one-program untraced path never quantizes.
        self._codec = resolve_codec(compression)
        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.dp_size = int(axis_sizes.get("data", 1))
        self.shard_update = bool(shard_update) and self.dp_size > 1
        self._warned_untraced = False
        if self._codec is not None and not self.shard_update:
            raise ValueError(
                f"compression={compression!r} requires shard_update=True "
                f"on a mesh with data>1 (data={self.dp_size}) — the "
                f"quantized wire exists only in the traced sharded bucket "
                f"programs; it would be silently ignored here")

        def clip_spec_fn(shape):
            return self._norm_spec(shape)

        if optimizer is not None:
            self.optimizer = optimizer
        elif optimizer_factory is not None:
            self.optimizer = optimizer_factory(
                clip_spec_fn if self.shard_update else None)
        else:
            self.optimizer = make_optimizer(
                clip_spec_fn=clip_spec_fn if self.shard_update else None)

        def init_boxed(rng):
            B, S = 1, min(cfg.max_seq_len, 128)
            tokens = jax.numpy.zeros((B, S), dtype=jax.numpy.int32)
            params = self.model.init(rng, tokens)["params"]
            opt_state = self.optimizer.init(params)
            return params, opt_state

        def init_fn(rng):
            # state is plain trees everywhere (grads, opt state, published
            # weights); the logical-partition boxes only feed the spec
            # derivation below
            return nn.unbox(init_boxed(rng))

        abstract = jax.eval_shape(init_boxed, jax.random.PRNGKey(0))
        logical = nn.get_partition_spec(abstract)
        shardings = logical_to_mesh_sharding(logical, mesh, rules)
        self.param_shardings, self.opt_shardings = shardings
        self.batch_sharding = NamedSharding(mesh, P(("data", "fsdp"), "seq"))
        self.repl = NamedSharding(mesh, P())
        self._abstract_params, self._abstract_opt = nn.unbox(abstract)

        # cross-replica update shardings: each leaf gains the "data" axis
        # on its first dim that can absorb it (opt state + grads; params
        # keep their logical shardings — they are consumed replicated on
        # data and re-emitted replicated via the program's all-gather)
        self.grad_shardings = jax.tree_util.tree_map(
            self._update_sharding, self._abstract_params,
            self.param_shardings)
        self.opt_shard_shardings = self._opt_update_shardings()

        self.init = jax.jit(init_fn, out_shardings=shardings)
        self.init_sharded = jax.jit(
            init_fn,
            out_shardings=(self.param_shardings, self.opt_shard_shardings))

        def loss_fn(params, tokens, targets, mask):
            # "losses" is valid for dense models too (empty -> aux sums to 0)
            logits, cols = self.model.apply(
                {"params": params}, tokens, mutable=["losses"])
            aux = sum(jax.tree.leaves(cols.get("losses", {})))
            return lm_loss(logits, targets, mask) + cfg.moe_aux_coef * aux

        self._loss_fn = loss_fn

        def train_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch["tokens"], batch["targets"], batch.get("mask"))
            grads = self._narrow_grads(grads)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        batch_shardings = {"tokens": self.batch_sharding,
                           "targets": self.batch_sharding,
                           "mask": self.batch_sharding}
        self._batch_shardings = batch_shardings
        donate_args = (0, 1) if donate else ()
        self._fused_step = jax.jit(
            train_step,
            in_shardings=(self.param_shardings, self.opt_shardings,
                          batch_shardings),
            out_shardings=(self.param_shardings, self.opt_shardings, self.repl),
            donate_argnums=donate_args,
        )
        # the SHARDED single-program step (the untraced perf path with
        # shard_update on): same program text, opt state in/out sharded
        # across data — the partitioner emits reduce-scatter for the
        # grads, shard-local update math, and an all-gather for the
        # updated params, all overlappable by XLA's async collectives.
        # Bit-exact vs _fused_step (tests/test_train.py pins it).
        self._fused_step_sharded = jax.jit(
            train_step,
            in_shardings=(self.param_shardings, self.opt_shard_shardings,
                          batch_shardings),
            out_shardings=(self.param_shardings, self.opt_shard_shardings,
                           self.repl),
            donate_argnums=donate_args,
        ) if self.shard_update else None

        # phase-split programs for the TRACED step (fwd+bwd and optimizer
        # as separate XLA programs, so tracing.profile() spans can bound
        # each phase); the untraced path keeps the fused program — and its
        # fusion/donation — untouched
        def fwd_bwd(params, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch["tokens"], batch["targets"], batch.get("mask"))
            return loss, self._narrow_grads(grads)

        self._fwd_bwd = jax.jit(
            fwd_bwd,
            in_shardings=(self.param_shardings, batch_shardings),
            out_shardings=(self.repl, self.param_shardings),
        )
        # sharded-update flavor: grads leave the backward already
        # reduce-scattered onto the data axis
        self._fwd_bwd_rs = jax.jit(
            fwd_bwd,
            in_shardings=(self.param_shardings, batch_shardings),
            out_shardings=(self.repl, self.grad_shardings),
        ) if self.shard_update else None

        def opt_apply(grads, opt_state, params):
            import optax

            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        # donation restored on the split path (PR 10 left grads undonated
        # to dodge XLA alias warnings). An optimizer program has one more
        # param-shaped input than output (grads + state + params ->
        # state' + params'), so exactly one donated input can never alias;
        # the warning-free maximal sets differ per flavor:
        # - unsharded: donate grads + opt_state — params' aliases the
        #   grads buffer (same full shape), params-in stays live as the
        #   read-only weight-decay/apply operand;
        # - sharded: donate opt_state + params — params' (all-gathered,
        #   full shape) aliases params-in, the 1/N grad shard is the
        #   pigeonhole leftover and stays undonated.
        # tests/test_train.py asserts the log is free of alias warnings.
        self._opt_apply = jax.jit(
            opt_apply,
            in_shardings=(self.param_shardings, self.opt_shardings,
                          self.param_shardings),
            out_shardings=(self.param_shardings, self.opt_shardings),
            donate_argnums=(0, 1) if donate else (),
        )
        self._opt_apply_sharded = jax.jit(
            opt_apply,
            in_shardings=(self.grad_shardings, self.opt_shard_shardings,
                          self.param_shardings),
            out_shardings=(self.param_shardings, self.opt_shard_shardings),
            donate_argnums=(1, 2) if donate else (),
        ) if self.shard_update else None

        # explicit bucketed tier (traced sharded path): needs a pure-DP
        # mesh (every non-data axis size 1) so params fit shard_map's
        # replicated in_spec without materializing gathers
        self._explicit_ok = self.shard_update and all(
            axis_sizes.get(a, 1) == 1 for a in AXES if a != "data")
        self._fwd_bwd_local = None
        self._bucket_programs: Optional[List] = None
        self._bucket_plan = None

        def eval_step(params, batch):
            logits, _ = self.model.apply(
                {"params": params}, batch["tokens"], mutable=["losses"])
            return lm_loss(logits, batch["targets"], batch.get("mask"))

        self.eval_step = jax.jit(eval_step)

        # shape/dtype-keyed compile detection for the goodput ledger: a
        # batch key this bundle has not dispatched before means jit will
        # block the call through trace+lower+compile — that wall time is
        # ``compile``, not ``step_compute``, and a NEW key on a warm
        # program is the recompile(-storm) signal
        from ray_tpu.util import goodput as _goodput

        self._compile_watch = _goodput.CompileWatch()

    # -- sharding helpers -------------------------------------------------

    def _narrow_grads(self, grads):
        """``grad_dtype="bf16"``: round grads through bf16 before the
        optimizer. On the one-program path this narrows the values the
        cross-replica reduction consumes (the collective's placement is
        XLA's; the traced bucket programs make the bf16 wire explicit);
        opt state and params remain fp32 master copies. A no-op at
        fp32 — the default program is untouched."""
        if self.grad_dtype != "bf16":
            return grads
        jax = import_jax()
        import jax.numpy as jnp

        return jax.tree_util.tree_map(
            lambda g: g.astype(jnp.bfloat16).astype(g.dtype), grads)

    def _update_sharding(self, abstract_leaf, base_sharding):
        """The cross-replica update sharding for one leaf: append the
        ``data`` axis to the first dim that can absorb it (dim size
        divisible by the dim's existing shard count x dp); leaves with no
        such dim stay on their base sharding (replicated update)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        shape = tuple(getattr(abstract_leaf, "shape", ()))
        if not self.shard_update or not shape:
            return base_sharding
        axis_sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        spec = list(getattr(base_sharding, "spec", P()) or P())
        spec += [None] * (len(shape) - len(spec))
        for d, size in enumerate(shape):
            entry = spec[d]
            axes = (() if entry is None
                    else (entry,) if isinstance(entry, str) else tuple(entry))
            if "data" in axes:
                return base_sharding  # already data-sharded
            existing = int(np.prod([axis_sizes.get(a, 1) for a in axes])) \
                if axes else 1
            if size % (existing * self.dp_size) == 0:
                spec[d] = tuple(axes) + ("data",) if axes else "data"
                return NamedSharding(self.mesh, P(*spec))
        return base_sharding

    def _norm_spec(self, shape: Tuple[int, ...]):
        """Shape-only reduction layout for the sharded clip (must be a
        pure function of shape so every program pins the same
        association)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if not shape:
            return None
        for d, size in enumerate(shape):
            if size % self.dp_size == 0:
                spec = [None] * len(shape)
                spec[d] = "data"
                return NamedSharding(self.mesh, P(*spec))
        return None

    def _opt_update_shardings(self):
        """Opt-state shardings for the sharded update: every leaf derives
        its own update sharding from its shape + base sharding — for
        adam-family moments (which mirror a param leaf's shape AND base
        sharding, both coming from the same flax spec derivation) this
        lands on exactly the matching param's update sharding; scalars and
        odd leaves stay on their base sharding."""
        jax = import_jax()

        return jax.tree_util.tree_map(self._update_sharding,
                                      self._abstract_opt,
                                      self.opt_shardings)

    # -- state conversion -------------------------------------------------

    def shard_opt_state(self, opt_state):
        """Reshard an (unsharded) opt state onto the cross-replica update
        shardings (adopting state from a fused-step run)."""
        jax = import_jax()

        return jax.device_put(opt_state, self.opt_shard_shardings)

    def unshard_opt_state(self, opt_state):
        """Gather a sharded opt state back onto the fused-step shardings
        (checkpointing through consumers that expect the base layout)."""
        jax = import_jax()

        return jax.device_put(opt_state, self.opt_shardings)

    def opt_state_bytes_per_replica(self, opt_state) -> int:
        """Per-device bytes of this opt state (sharded leaves count one
        shard; replicated leaves count in full — the honest per-replica
        cost)."""
        jax = import_jax()

        total = 0
        for leaf in jax.tree_util.tree_leaves(opt_state):
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                total += int(np.asarray(shards[0].data).nbytes)
            else:
                total += int(np.asarray(leaf).nbytes)
        return total

    def opt_state_bytes_total(self) -> int:
        """Unsharded footprint of one full optimizer state (from the
        abstract tree — no state needs to be materialized)."""
        jax = import_jax()

        total = 0
        for leaf in jax.tree_util.tree_leaves(self._abstract_opt):
            shape = tuple(getattr(leaf, "shape", ()))
            itemsize = np.dtype(leaf.dtype).itemsize
            total += (int(np.prod(shape, dtype=np.int64)) * itemsize
                      if shape else itemsize)
        return total

    # -- bucket plan + explicit bucketed programs -------------------------

    @property
    def bucket_plan(self):
        """Layer-ordered size-bounded bucket plan over the grad tree
        (shared with the collective tier — collective/bucketed.py)."""
        if self._bucket_plan is None:
            from ray_tpu.collective.bucketed import leaf_meta, plan_buckets

            self._bucket_plan = plan_buckets(
                leaf_meta(self._abstract_params),
                bucket_bytes=self.bucket_bytes,
                world_size=self.dp_size)
        return self._bucket_plan

    def _build_explicit(self):
        """The traced sharded tier: a shard_map backward emitting stacked
        per-replica local grads, plus one jitted reduce-scatter program
        per bucket. Built lazily — only the traced path pays the
        compiles."""
        if self._fwd_bwd_local is not None:
            return
        jax = import_jax()
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        loss_fn = self._loss_fn
        dp = self.dp_size
        bspec = P(("data", "fsdp"), "seq")

        def local_fb(params, tokens, targets, mask):
            def f(p, t, tg, m):
                loss, g = jax.value_and_grad(loss_fn)(p, t, tg, m)
                # the fused step's gradient weights every token by
                # 1/sum(global mask); the local loss normalized by the
                # LOCAL mask sum would make sparse replicas count extra
                # (mean-of-means). Reweight each replica's grads by
                # m_local * dp / m_global — exactly 1.0 for equal-count
                # shards (the bit-parity case), the fused weighting
                # otherwise. The bucket programs' trailing 1/dp folds the
                # dp factor back out.
                m_local = jnp.sum(m)
                m_global = jax.lax.psum(m_local, ("data", "fsdp"))
                w = (m_local * np.float32(dp) / m_global).astype(jnp.float32)
                g = jax.tree_util.tree_map(
                    lambda a: a * w.astype(a.dtype), g)
                return loss[None], m_local[None], jax.tree_util.tree_map(
                    lambda a: a[None], g)

            grad_specs = jax.tree_util.tree_map(lambda _: P("data"), params)
            return shard_map(
                f, mesh=mesh,
                in_specs=(P(), bspec, bspec, bspec),
                out_specs=(P("data"), P("data"), grad_specs),
                check_rep=False)(params, tokens, targets, mask)

        self._fwd_bwd_local = jax.jit(
            local_fb,
            in_shardings=(self.param_shardings, self.batch_sharding,
                          self.batch_sharding, self.batch_sharding))

        flat, _ = jax.tree_util.tree_flatten_with_path(self._abstract_params)
        by_path = {jax.tree_util.keystr(k): a for k, a in flat}
        gsh_flat, _ = jax.tree_util.tree_flatten_with_path(
            self.grad_shardings)
        sh_by_path = {jax.tree_util.keystr(k): s for k, s in gsh_flat}
        inv = np.float32(1.0 / dp)

        def _data_dim(sharding) -> Optional[int]:
            """The leaf dim carrying the ``data`` axis in its update
            sharding (the reduce-scatter dim), or None (replicated)."""
            spec = tuple(getattr(sharding, "spec", P()) or P())
            for d, entry in enumerate(spec):
                axes = (() if entry is None
                        else (entry,) if isinstance(entry, str)
                        else tuple(entry))
                if "data" in axes:
                    return d
            return None

        codec = self._codec
        bf16_wire = self.grad_dtype == "bf16"

        def _q_rs_leaf(v, d):
            """Quantized reduce-scatter of one leaf on dim ``d``: split
            into per-owner parts along ``d``, block-quantize each part,
            ``all_to_all`` the uint8 codes + fp32 scales (the wire leg —
            1 byte/element instead of 4), dequant-accumulate in fp32.
            Output == psum_scatter(v, scatter_dimension=d, tiled=True) to
            quantization error. Stateless (no error feedback) — EF lives
            in the explicit tier where residuals can persist."""
            from ray_tpu.collective.quant import jnp_block_encode

            block = codec.block
            vm = jnp.moveaxis(v, d, 0)
            rest = vm.shape[1:]
            seg = vm.shape[0] // dp
            flat = vm.reshape(dp, -1)
            m = flat.shape[1]
            nb = -(-m // block)
            if nb * block != m:
                flat = jnp.pad(flat, ((0, 0), (0, nb * block - m)))
            if codec.name == "bf16":  # narrow wire dtype, no scales
                qg = jax.lax.all_to_all(
                    flat.reshape(dp, nb * block).astype(jnp.bfloat16),
                    "data", split_axis=0, concat_axis=0, tiled=False)
                summed = jnp.sum(qg.astype(jnp.float32), axis=0)[:m]
                return jnp.moveaxis(summed.reshape((seg,) + rest), 0, d)
            q, scale = jnp_block_encode(flat.reshape(dp, nb, block),
                                        codec.name)
            qg = jax.lax.all_to_all(q, "data", split_axis=0, concat_axis=0,
                                    tiled=False)
            sg = jax.lax.all_to_all(scale, "data", split_axis=0,
                                    concat_axis=0, tiled=False)
            vals = qg.astype(jnp.float32) * sg[..., None]
            summed = jnp.sum(vals, axis=0).reshape(-1)[:m]
            return jnp.moveaxis(summed.reshape((seg,) + rest), 0, d)

        def make_bucket_rs(paths):
            dims = [_data_dim(sh_by_path[p]) for p in paths]

            def f(*stacked):
                outs = []
                for x, d in zip(stacked, dims):
                    if d is not None and codec is not None:
                        # quantized wire; tiny/replicated leaves below
                        # stay fp32 (QUANT.md: never quantize the
                        # few-float legs)
                        y = _q_rs_leaf(x[0], d)
                    elif d is not None and bf16_wire:
                        y = jax.lax.psum_scatter(
                            x[0].astype(jnp.bfloat16), "data",
                            scatter_dimension=d,
                            tiled=True).astype(jnp.float32)
                    elif d is not None:
                        y = jax.lax.psum_scatter(
                            x[0], "data", scatter_dimension=d, tiled=True)
                    else:
                        y = jax.lax.psum(x[0], "data")
                    outs.append(y * inv)
                return tuple(outs)

            def out_spec(d, path):
                if d is None:
                    return P()
                ndim = len(by_path[path].shape)
                entries = [None] * ndim
                entries[d] = "data"
                return P(*entries)

            in_specs = tuple(P("data") for _ in paths)
            out_specs = tuple(out_spec(d, p) for d, p in zip(dims, paths))
            return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                                     out_specs=out_specs, check_rep=False))

        self._bucket_programs = [
            (bucket, make_bucket_rs(bucket.paths))
            for bucket in self.bucket_plan.buckets
        ]
        self._grad_paths = [jax.tree_util.keystr(k) for k, _ in flat]
        _, self._grad_treedef = jax.tree_util.tree_flatten(
            self._abstract_params)

    def _step_traced_sharded(self, params, opt_state, batch):
        """Traced sharded step: local backward, per-bucket async reduce-
        scatter programs (each one a ``train.bucket_allreduce`` span
        nested under ``train.fwd_bwd``), then the sharded optimizer
        program. Matches the untraced sharded step to fp32 tolerance (the
        per-replica backward uses local-batch kernel shapes, so parity
        with the single-program path is allclose, not bitwise — see
        OVERLAP.md)."""
        jax = import_jax()
        from ray_tpu.util import tracing

        obs = _obs()
        self._build_explicit()
        with tracing.profile("train.step", category="train"):
            with tracing.profile("train.fwd_bwd", category="train",
                                 buckets=self.bucket_plan.num_buckets):
                t1 = time.perf_counter()
                losses, mask_counts, local_grads = self._fwd_bwd_local(
                    params, batch["tokens"], batch["targets"],
                    batch.get("mask"))
                flat = jax.tree_util.tree_leaves(local_grads)
                by_path = dict(zip(self._grad_paths, flat))
                # issue every bucket's reduce-scatter asynchronously as
                # soon as the backward's outputs exist; waits happen per
                # bucket so the spans bound real completion
                dispatched = []
                for bucket, prog in self._bucket_programs:
                    dispatched.append(
                        (bucket, prog(*[by_path[p] for p in bucket.paths])))
                reduced: Dict[str, Any] = {}
                for bucket, outs in dispatched:
                    tb = time.perf_counter()
                    with tracing.profile("train.bucket_allreduce",
                                         category="train",
                                         bucket=bucket.index,
                                         nbytes=bucket.nbytes,
                                         leaves=len(bucket.paths)):
                        jax.block_until_ready(outs)
                    obs["bucket_rs"].observe(time.perf_counter() - tb)
                    reduced.update(dict(zip(bucket.paths, outs)))
                grads = jax.tree_util.tree_unflatten(
                    self._grad_treedef,
                    [reduced[p] for p in self._grad_paths])
                obs["fwd_bwd"].observe(time.perf_counter() - t1)
            with tracing.profile("train.optimizer", category="train"):
                t2 = time.perf_counter()
                params, opt_state = self._opt_apply_sharded(
                    grads, opt_state, params)
                jax.block_until_ready(params)
                obs["optimizer"].observe(time.perf_counter() - t2)
        import jax.numpy as jnp

        # mask-count-weighted mean of the per-replica losses (the fused
        # step's global normalization, modulo the aux term's replica mean)
        loss = jnp.sum(losses * mask_counts) / jnp.maximum(
            jnp.sum(mask_counts), 1.0)
        return params, opt_state, loss

    # -- the step ---------------------------------------------------------

    def step(self, params, opt_state, batch):
        """One optimization step, instrumented (built-in spans + the
        ``ray_tpu.train.*`` histograms — no manual instrumentation in the
        train loop). With tracing OFF this dispatches ONE fused XLA
        program — the sharded-update flavor when ``shard_update`` is on
        (opt state must be on the sharded layout, e.g. from
        ``init_sharded`` / ``shard_opt_state``), the plain fused program
        otherwise. With tracing ON the step runs as separately-jitted
        phase programs under a ``train.step`` span tree — including
        per-bucket ``train.bucket_allreduce`` spans on the sharded
        path — so Perfetto shows where the step time goes."""
        from ray_tpu.util import goodput, tracing

        t0 = time.perf_counter()
        if not tracing.enabled():
            if self._codec is not None and not self._warned_untraced:
                # the quantized bucket programs only exist on the traced
                # path — surface the silent-fp32 trap instead of letting
                # benchmarks report compression that never engaged
                self._warned_untraced = True
                import logging

                logging.getLogger(__name__).warning(
                    "TrainStepBundle(compression=%s): tracing is "
                    "disabled, so this step runs the fused fp32 program "
                    "— the quantized wire needs tracing ON "
                    "(RAY_TPU_ENABLE_TRACING=1)", self._codec.spec())
            fn = (self._fused_step_sharded if self.shard_update
                  else self._fused_step)
            program = "fused_sharded" if self.shard_update else "fused"
            out = self._dispatch_attributed(program, fn, params, opt_state,
                                            batch)
            _obs()["step"].observe(time.perf_counter() - t0)
            return out
        if (self.shard_update and self._explicit_ok
                and batch.get("mask") is not None):
            out = self._dispatch_attributed(
                "traced_sharded", self._step_traced_sharded, params,
                opt_state, batch)
            _obs()["step"].observe(time.perf_counter() - t0)
            return out
        jax = import_jax()
        obs = _obs()
        fwd = self._fwd_bwd_rs if self.shard_update else self._fwd_bwd
        opt = self._opt_apply_sharded if self.shard_update else self._opt_apply
        kind = self._compile_watch.observe(
            "phases_rs" if self.shard_update else "phases",
            goodput.batch_key(batch))
        with goodput.region("step_compute"), \
                goodput.region("compile") if kind else _nullcontext():
            with tracing.profile("train.step", category="train"):
                with tracing.profile("train.fwd_bwd", category="train"):
                    t1 = time.perf_counter()
                    loss, grads = fwd(params, batch)
                    jax.block_until_ready(grads)
                    obs["fwd_bwd"].observe(time.perf_counter() - t1)
                with tracing.profile("train.optimizer", category="train"):
                    t2 = time.perf_counter()
                    params, opt_state = opt(grads, opt_state, params)
                    jax.block_until_ready(params)
                    obs["optimizer"].observe(time.perf_counter() - t2)
        goodput.count("steps")
        if kind:
            goodput.count("compiles")
            if kind == "recompile":
                goodput.count("recompiles")
        obs["step"].observe(time.perf_counter() - t0)
        return params, opt_state, loss

    def _dispatch_attributed(self, program, fn, params, opt_state, batch):
        """Dispatch one step program under the goodput ledger:
        ``step_compute`` normally; a compile-watch miss (new batch
        shape/dtype key) routes the call — which jit blocks through
        trace+lower+compile — into the nested ``compile`` bucket, with
        the outputs synced so compile wall time is fully captured."""
        from ray_tpu.util import goodput

        kind = self._compile_watch.observe(program, goodput.batch_key(batch))
        with goodput.region("step_compute"):
            if kind is None:
                out = fn(params, opt_state, batch)
            else:
                with goodput.region("compile"):
                    out = fn(params, opt_state, batch)
                    import_jax().block_until_ready(out)
        goodput.count("steps")
        if kind:
            goodput.count("compiles")
            if kind == "recompile":
                goodput.count("recompiles")
        return out

    def make_batch(self, rng: np.random.Generator, batch_size: int, seq_len: int):
        """Synthetic LM batch (tokens/targets/mask) laid out for the mesh."""
        jax = import_jax()

        tokens = rng.integers(0, self.cfg.vocab_size, (batch_size, seq_len + 1),
                              dtype=np.int32)
        batch = {
            "tokens": tokens[:, :-1],
            "targets": tokens[:, 1:],
            "mask": np.ones((batch_size, seq_len), np.float32),
        }
        return {k: jax.device_put(v, self.batch_sharding) for k, v in batch.items()}

"""Runtime context (reference: python/ray/runtime_context.py)."""

from __future__ import annotations

from typing import Optional


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    @property
    def job_id(self):
        return self._worker.job_id

    def get_job_id(self) -> str:
        return self._worker.job_id.hex()

    def get_task_id(self) -> Optional[str]:
        tid = getattr(self._worker, "current_task_id", None)
        return tid.hex() if tid is not None else None

    def get_actor_id(self) -> Optional[str]:
        aid = getattr(self._worker, "current_actor_id", None)
        return aid.hex() if aid is not None else None

    def get_node_id(self) -> Optional[str]:
        nid = getattr(self._worker, "node_id", None)
        if nid is not None:
            return nid.hex()
        # drivers connect to an existing raylet: only the hex is recorded
        return getattr(self._worker, "node_hex", None) or None

    def get_worker_id(self) -> Optional[str]:
        wid = getattr(self._worker, "worker_id", None)
        return wid.hex() if wid is not None else None

    @property
    def namespace(self) -> str:
        return getattr(self._worker, "namespace", "default")

    def get_assigned_resources(self):
        return dict(getattr(self._worker, "assigned_resources", {}) or {})

    def current_actor(self):
        from ray_tpu._private import worker as _worker

        aid = getattr(self._worker, "current_actor_id", None)
        if aid is None:
            raise RuntimeError("not running inside an actor")
        return _worker.global_worker().get_actor_handle(aid)


def get_runtime_context() -> RuntimeContext:
    from ray_tpu._private import worker as _worker

    return RuntimeContext(_worker.global_worker())

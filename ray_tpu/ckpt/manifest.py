"""Checkpoint manifests: the durable unit of the checkpoint plane.

A checkpoint is an immutable *manifest* plus a set of content-addressed
*chunk* files:

- a chunk is the bytes of one shard box of one array leaf (or one opaque
  pickled non-array leaf), named by the SHA-256 of its bytes and stored
  under ``<root>/chunks/<hh>/<hash>``. Identical bytes — e.g. a frozen
  embedding table that did not change between steps — hash to the same
  file, so consecutive saves share chunks and an incremental save writes
  only the delta;
- the manifest records the tree skeleton, the sharded-tree geometry
  (``weights.spec.ShardedTreeSpec`` payload), every leaf's chunk list
  ``(box, hash, nbytes)``, the parent checkpoint id, user metrics, and
  byte-accounting stats. It is serialized as JSON under
  ``<root>/manifests/<ckpt_id>.json``.

Atomicity invariant: every file of the checkpoint layout — chunks,
manifests, the ``LATEST`` pointer, pins, saver part-files — is written
through :func:`atomic_write` (write temp + fsync + rename). A reader can
never observe a torn file: either the old bytes or the new bytes, and
``LATEST`` only moves *after* its manifest (and all chunks the manifest
names) are durable. A crash mid-save leaves stray temp files and possibly
orphan chunks (garbage-collected by retention), never a visible partial
checkpoint. raylint rule CKP001 enforces that no checkpoint-plane code
opens a file for writing outside this helper.

The geometry intentionally matches the weight plane (PR 2): the same
``(leaf, box)`` chunk model means restore-time resharding reuses
``weights/plan.py`` verbatim — a restore onto a different mesh reads only
the chunk bytes intersecting each host's destination boxes and never
gathers a full leaf anywhere.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

Box = Tuple[Tuple[int, int], ...]

# leaf kinds
ND = "nd"  # numpy array: raw C-order bytes, shardable by box
PY = "py"  # opaque python leaf: serialization.dumps_oob bytes, never sharded

MANIFEST_DIR = "manifests"
CHUNK_DIR = "chunks"
PART_DIR = "parts"
LATEST_FILE = "LATEST"
PINS_FILE = "PINS"


# ---------------------------------------------------------------------------
# the single write chokepoint (raylint CKP001)
# ---------------------------------------------------------------------------


def atomic_write(path: str, data: bytes) -> None:
    """Crash-safe file write: temp file + fsync + rename into place.

    The rename is atomic on POSIX, so concurrent readers see either the
    previous content or the full new content — never a torn file. The
    temp name carries pid+nonce so concurrent writers of the same target
    (two hosts racing on the same content-addressed chunk) cannot clobber
    each other's temp file; last rename wins with identical bytes.
    """
    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "wb") as f:  # raylint: disable=CKP001 this IS the helper
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # make the rename itself durable (the dirent lives in the directory)
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


# ---------------------------------------------------------------------------
# boxes / chunk keys (same codec as the weight plane)
# ---------------------------------------------------------------------------


def encode_box(box: Optional[Box]) -> str:
    if box is None:
        return ""
    return ",".join(f"{a}:{b}" for a, b in box)


def decode_box(s: str) -> Optional[Box]:
    if not s:
        return None
    return tuple(tuple(int(x) for x in part.split(":")) for part in s.split(","))


def chunk_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def chunk_path(root: str, h: str) -> str:
    return os.path.join(root, CHUNK_DIR, h[:2], h)


def write_chunk(root: str, data: bytes) -> Tuple[str, bool]:
    """Store ``data`` content-addressed. Returns ``(hash, created)`` —
    ``created=False`` is the dedup hit: the bytes already exist on disk
    and nothing is written."""
    h = chunk_hash(data)
    path = chunk_path(root, h)
    if os.path.exists(path):
        return h, False
    atomic_write(path, data)
    return h, True


def read_chunk(root: str, h: str) -> bytes:
    with open(chunk_path(root, h), "rb") as f:
        return f.read()


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LeafEntry:
    """One leaf's chunk list. For ``kind == ND``, ``chunks`` maps encoded
    shard boxes (global coordinates) to ``(hash, nbytes)``; for ``PY`` a
    single entry under the empty box."""

    kind: str
    shape: Tuple[int, ...]
    dtype: str
    chunks: Dict[str, Tuple[str, int]]

    def to_json(self) -> dict:
        return {"kind": self.kind, "shape": list(self.shape),
                "dtype": self.dtype,
                "chunks": {k: [h, n] for k, (h, n) in self.chunks.items()}}

    @classmethod
    def from_json(cls, d: dict) -> "LeafEntry":
        return cls(kind=d["kind"], shape=tuple(d["shape"]), dtype=d["dtype"],
                   chunks={k: (v[0], int(v[1]))
                           for k, v in d["chunks"].items()})


@dataclasses.dataclass
class Manifest:
    ckpt_id: str
    step: int
    ts: float
    parent: Optional[str]
    skeleton: Any
    spec: Optional[dict]  # ShardedTreeSpec payload (weights.store codec)
    leaves: Dict[str, LeafEntry]
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # -- accounting ----------------------------------------------------

    def chunk_set(self) -> Dict[str, int]:
        """hash -> nbytes over every chunk this manifest references
        (deduplicated: a chunk shared by two leaves counts once)."""
        out: Dict[str, int] = {}
        for entry in self.leaves.values():
            for h, n in entry.chunks.values():
                out[h] = n
        return out

    def total_bytes(self) -> int:
        return sum(n for _, entry in sorted(self.leaves.items())
                   for _, n in entry.chunks.values())

    def to_json(self) -> dict:
        return {
            "version": 1,
            "ckpt_id": self.ckpt_id,
            "step": self.step,
            "ts": self.ts,
            "parent": self.parent,
            "skeleton": self.skeleton,
            "spec": self.spec,
            "leaves": {k: v.to_json() for k, v in sorted(self.leaves.items())},
            "metrics": self.metrics,
            "stats": self.stats,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Manifest":
        return cls(
            ckpt_id=d["ckpt_id"], step=int(d["step"]), ts=float(d["ts"]),
            parent=d.get("parent"), skeleton=d["skeleton"],
            spec=d.get("spec"),
            leaves={k: LeafEntry.from_json(v)
                    for k, v in d["leaves"].items()},
            metrics=d.get("metrics") or {},
            stats=d.get("stats") or {},
        )


def new_ckpt_id(step: int) -> str:
    """Sortable-by-step, collision-free id."""
    return f"step{int(step):010d}-{uuid.uuid4().hex[:8]}"


def manifest_path(root: str, ckpt_id: str) -> str:
    return os.path.join(root, MANIFEST_DIR, f"{ckpt_id}.json")


def write_manifest(root: str, manifest: Manifest) -> str:
    """Persist the manifest (atomically). Does NOT move ``LATEST`` — that
    is the separate, last step of a commit (see ``commit``)."""
    path = manifest_path(root, manifest.ckpt_id)
    atomic_write(path, json.dumps(manifest.to_json(), sort_keys=True,
                                  default=_json_default).encode())
    return path


def _json_default(v):
    try:
        import numpy as np

        if isinstance(v, np.generic):
            return v.item()
        if isinstance(v, np.ndarray):
            return v.tolist()
    except ImportError:
        pass
    raise TypeError(f"manifest field of type {type(v).__name__} is not "
                    f"JSON-encodable")


def read_manifest(root: str, ckpt_id: str) -> Manifest:
    with open(manifest_path(root, ckpt_id)) as f:
        return Manifest.from_json(json.load(f))


def commit(root: str, manifest: Manifest) -> None:
    """The atomic publish: manifest file first, then the ``LATEST``
    pointer. A crash between the two leaves a valid (restorable, listable)
    checkpoint that simply is not ``latest`` yet; a crash before the
    manifest write leaves only orphan chunks, invisible to every reader."""
    write_manifest(root, manifest)
    atomic_write(os.path.join(root, LATEST_FILE),
                 json.dumps({"ckpt_id": manifest.ckpt_id,
                             "step": manifest.step,
                             "ts": manifest.ts}).encode())


def read_latest_id(root: str) -> Optional[str]:
    """The committed ``LATEST`` pointer, validated against the manifest it
    names (a pointer to a missing/torn manifest is ignored — restore then
    falls back to the newest listable checkpoint)."""
    try:
        with open(os.path.join(root, LATEST_FILE)) as f:
            ckpt_id = json.load(f)["ckpt_id"]
    except (FileNotFoundError, json.JSONDecodeError, KeyError):
        return None
    try:
        read_manifest(root, ckpt_id)
    except (FileNotFoundError, json.JSONDecodeError, KeyError, ValueError):
        return None
    return ckpt_id


def list_manifest_ids(root: str) -> List[str]:
    """Every *valid* manifest id, sorted oldest-first (step, then commit
    ts). Torn or unparsable manifest files are skipped, not raised — a
    crashed save must not poison listing."""
    mdir = os.path.join(root, MANIFEST_DIR)
    try:
        names = os.listdir(mdir)
    except FileNotFoundError:
        return []
    rows = []
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(mdir, name)) as f:
                d = json.load(f)
            rows.append((int(d["step"]), float(d["ts"]), d["ckpt_id"]))
        except (json.JSONDecodeError, KeyError, ValueError, OSError):
            continue
    rows.sort()
    return [cid for _, _, cid in rows]


# ---------------------------------------------------------------------------
# diff: what actually changed between two checkpoints
# ---------------------------------------------------------------------------


def diff_manifests(a: Manifest, b: Manifest) -> Dict[str, Any]:
    """Chunk-level delta between two checkpoints: shared bytes (stored
    once thanks to content addressing), bytes only in each side, and the
    leaves whose chunk sets differ."""
    ca, cb = a.chunk_set(), b.chunk_set()
    shared = set(ca) & set(cb)
    only_a = set(ca) - shared
    only_b = set(cb) - shared
    changed_leaves = sorted(
        leaf for leaf in set(a.leaves) | set(b.leaves)
        if (ea := a.leaves.get(leaf)) is None or (eb := b.leaves.get(leaf)) is None
        or {h for h, _ in ea.chunks.values()} != {h for h, _ in eb.chunks.values()})
    total_b = sum(cb.values())
    return {
        "a": a.ckpt_id, "b": b.ckpt_id,
        "shared_chunks": len(shared),
        "shared_bytes": sum(ca[h] for h in shared),
        "only_a_chunks": len(only_a),
        "only_a_bytes": sum(ca[h] for h in only_a),
        "only_b_chunks": len(only_b),
        "only_b_bytes": sum(cb[h] for h in only_b),
        "changed_leaves": changed_leaves,
        "dedup_ratio": (sum(cb[h] for h in shared) / total_b)
        if total_b else 1.0,
    }
